"""Elimination-tree build as a data-parallel fixpoint (SURVEY.md §2 #4-6).

This is the TPU answer to the reference's sequential union-find hot loop
(SURVEY.md §7 hard part #1). Instead of pointer-chasing per edge, the
build is a *constraint-rewriting fixpoint*: the carried forest lives in a
persistent ``minp`` table (minp[x] = elimination position of x's parent,
n = none) and only the chunk's C edges are ever active:

    invariant  pos[lo] < pos[hi] for every active edge (lo, hi)
    round:
      minp[x] <- min(minp[x], pos of hi over active edges at lo=x)
                                                          (scatter-min)
      an active edge (x, v) with pos[v] == minp[x] RETIRES — it is now
      represented by the table. If it improved the table (old parent p
      had pos[p] > pos[v]), the displaced constraint "x ~ p from
      pos[p]" reduces to "v ~ p from pos[p]" (x~v merged strictly
      earlier), so the retiring slot is REUSED in place for (v, p).
      every other active edge (x, v) climbs: rewrite to (m, v) where m
      is x's highest ancestor with pos[m] < pos[v]          (gather)
    fixpoint: all slots dead -> the table is the elimination forest of
    every constraint inserted so far.

This is the vectorized form of the C++ core's incremental insertion
(core/csrc/sheep_core.cpp insert_edge: climb / displace-and-reinsert);
the represented constraint closure is preserved by every rewrite, so the
fixpoint is the unique elimination forest of the inserted multiset,
independent of edge order — which is what makes the build streamable and
the per-shard forests mergeable. Termination: a slot's pos[lo] strictly
increases on every climb AND on displacement spawn (the displaced
constraint's lo is the new parent, later than x), so each slot changes
at most n times; binary lifting makes it near-logarithmic in practice.

Unlike a formulation that re-materializes the carried forest's V tree
edges as active constraints each chunk, the active set here is O(C):
per-chunk transient memory and per-round work are independent of V
(BASELINE.md "HBM budget": single-chip ceiling 2^29 vertices at 16 GiB).

Every operation is a flat gather / scatter-min over static shapes; the
loop is a ``lax.while_loop``. Within each round the climb uses **binary
lifting** (pointer doubling): the parent map is squared ``lift_levels``
times (t_{j+1} = t_j[t_j], each a 2^j-step ancestor table) and every
edge jumps up the tables to its highest ancestor still earlier than
``hi``. Parent chains strictly increase in elimination position, so the
pos-bound predicate is monotone along a chain (measured: 645 -> 22
rounds on RMAT-14).

The round body runs entirely in **position space** (state = elimination
positions, table P[p] = parent position of the vertex at rank p): the
parent table then IS the first lifting table and jump admissibility is
a direct integer compare, cutting the gathers per level per slot from
three to one — and random-gather count is the entire round cost on a
real TPU (measured ~100-150 M gathers/s on v5e regardless of operand
shapes; tools/microbench_fixpoint.py). The public entry points keep the
vertex-space minp contract via exact permutation conversions; the
``*_pos`` variants let the streaming backend carry P across chunks with
zero steady-state conversions.

Two descent schedules, auto-selected by memory footprint:

- **exact** (high-to-low over precomputed tables): one round climbs each
  edge to its true highest admissible ancestor, fewest rounds, but all
  ``lift_levels`` tables are live at once -> O(V log V) working memory.
  Used while that fits ``EXACT_TABLE_BYTES`` (1 GiB default).
- **stream** (low-to-high, squaring interleaved with jumping): only one
  table is live -> O(V + C) memory, ~1.4x the rounds (greedy LSB-first
  jumping is not exact, but every taken jump is a sound rewrite, so the
  fixpoint is unchanged). Used for huge V where the table stack would
  blow HBM.

Sentinel encoding: index ``n`` means "none"; ``pos[n] = n`` acts as +inf,
``order[n] = n``. Inactive/padding edges are (n, n).
"""

from __future__ import annotations

import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from sheep_tpu.analysis import sanitize

NO_PARENT = -1


def pow2_at_least(x: int, floor: int = 1) -> int:
    """Smallest power of two >= max(x, 1), raised to at least ``floor``
    — the shared buffer-sizing rule (compactions, host-tail pulls,
    merge payload capacities): power-of-two sizes keep the set of
    compiled program shapes logarithmic in the starting width."""
    return max(floor, 1 << max(0, (max(int(x), 1) - 1).bit_length()))


@partial(jax.jit, static_argnames=("n",))
def orient_edges(edges: jax.Array, pos: jax.Array, n: int):
    """(C,2) int32 edges -> (lo, hi) with pos[lo] < pos[hi]; self-loops and
    out-of-range/padding endpoints become inactive (n, n)."""
    e = edges.astype(jnp.int32)
    u = jnp.clip(e[:, 0], 0, n)
    v = jnp.clip(e[:, 1], 0, n)
    pu, pv = pos[u], pos[v]
    lo = jnp.where(pu <= pv, u, v)
    hi = jnp.where(pu <= pv, v, u)
    bad = (lo == hi) | (pos[lo] == pos[hi])  # self-loop or both-sentinel
    lo = jnp.where(bad, n, lo)
    hi = jnp.where(bad, n, hi)
    return lo, hi


# exact descent keeps lift_levels ancestor tables of 4*(n+1) bytes live at
# once; beyond this budget the fixpoint switches to the O(V) stream descent
EXACT_TABLE_BYTES = 1 << 30


def _resolve(n: int, lift_levels: int, descent: str):
    if lift_levels <= 0:
        lift_levels = max(1, int(n).bit_length())
    if descent == "auto":
        table_bytes = lift_levels * 4 * (n + 1)
        descent = "exact" if table_bytes <= EXACT_TABLE_BYTES else "stream"
    return lift_levels, descent


def _pos_round_body(n: int, lift_levels: int, descent: str):
    """One fixpoint round as a while_loop body over POSITION-SPACE state
    (loP, hiP, P, changed, rounds) — shared by every entry point so all
    schedules execute identical rounds.

    Position space is the real-chip optimization (BASELINE.md roofline):
    with P[p] = elimination position of the parent of the vertex at rank
    p, the parent table IS the first binary-lifting table (ancestor
    chains strictly increase in position), and jump admissibility is the
    direct integer compare ``cand < hiP``. The vertex-space formulation
    needed three gathers per lifting level per slot (t[new_lo],
    pos[cand], plus the order[...] rewrites); this needs ONE — and XLA
    gather throughput is the whole cost of a round on TPU (measured
    ~100-150 M random gathers/s on v5e, tools/microbench_fixpoint.py).
    The dynamics commute with the pos/order permutation, so slot
    trajectories are bit-identical to the vertex-space form under
    ``order[.]``/``pos[.]`` conjugation."""

    def body(state):
        lo_, hi_, P_, _, rounds = state
        old_at_lo = P_[lo_]  # parent position BEFORE this round
        newP = P_.at[lo_].min(hi_, mode="drop")
        now = newP[lo_]

        # climb for non-retiring slots. t_j[p] = p's 2^j-step ancestor
        # position under the updated table (sentinel n is a fixpoint of
        # every table since P[n] = n); a jump is safe iff it lands
        # strictly earlier than hiP
        t = newP
        cur = lo_
        if descent == "exact":
            tables = [t]
            for _ in range(lift_levels - 1):
                t = t[t]
                tables.append(t)
            for t in reversed(tables):
                cand = t[cur]
                cur = jnp.where(cand < hi_, cand, cur)
        else:  # stream: square in place, only one table live
            for j in range(lift_levels):
                cand = t[cur]
                cur = jnp.where(cand < hi_, cand, cur)
                if j < lift_levels - 1:
                    t = t[t]
        became_loop = cur == hi_  # constraint already implied
        climb_lo = jnp.where(became_loop, n, cur)
        climb_hi = jnp.where(became_loop, n, hi_)

        # retire: this slot's target IS the min at lo (positions are
        # unique, so only duplicates of the same constraint retire
        # together). If it improved on an existing parent p, reuse the
        # slot for the displaced constraint (now, old); else it dies.
        retire = hi_ == now
        displaced = retire & (now < old_at_lo) & (old_at_lo < n)
        out_lo = jnp.where(retire,
                           jnp.where(displaced, now, n),
                           climb_lo).astype(jnp.int32)
        out_hi = jnp.where(retire,
                           jnp.where(displaced, old_at_lo, n),
                           climb_hi).astype(jnp.int32)
        # slots only ever change toward progress (loP strictly
        # increases), so "no slot changed" == fixpoint (table included:
        # the table only changes through a retiring slot)
        changed = jnp.any((out_lo != lo_) | (out_hi != hi_))
        return out_lo, out_hi, newP, changed, rounds + 1

    return body


def _init_state(minp, lo, hi):
    # derive the initial carry scalars from `lo` so their sharding/varying
    # axes match the loop body's outputs (required under shard_map)
    changed0 = lo[0] == lo[0]  # True, with lo's varying axes
    rounds0 = (lo[0] * 0).astype(jnp.int32)
    return (lo.astype(jnp.int32), hi.astype(jnp.int32),
            minp.astype(jnp.int32), changed0, rounds0)


def _run_segment(body, P, loP, hiP, n: int, segment_rounds: int):
    """Shared segment epilogue: bounded while_loop + the packed int32[3]
    stats vector (changed, rounds, live) — the cross-module contract
    read by the adaptive driver (one host pull) and the sharded
    pipeline (sv[0]/sv[2] pmax)."""
    def cond(state):
        _, _, _, changed, rounds = state
        return changed & (rounds < segment_rounds)

    loP, hiP, P, changed, rounds = lax.while_loop(
        cond, body, _init_state(P, loP, hiP))
    stats = jnp.stack([changed.astype(jnp.int32), rounds,
                       jnp.sum(loP != n, dtype=jnp.int32)])
    return loP, hiP, P, stats


def _pos_round_body_stale(n: int, tables: tuple):
    """Round body for :func:`fold_segment_pos_hoisted`: identical
    retire/displace semantics to :func:`_pos_round_body` (exact
    descent), but the lifting tables above level 0 are STALE closures —
    built once per segment — while level 0 is always the CURRENT table.
    Sound because ancestor-ship is permanent (when a parent improves,
    the displaced constraint re-links the old parent above the new
    one), so a stale table's jumps land on genuine — just possibly
    non-maximal — ancestors; any progress missed is caught after the
    next rebuild. Saves (R-1)/R of the L x V squaring gathers per
    segment, the round's dominant V-term (BASELINE.md 'stale lifting
    tables')."""

    def body(state):
        lo_, hi_, P_, _, rounds = state
        old_at_lo = P_[lo_]
        newP = P_.at[lo_].min(hi_, mode="drop")
        now = newP[lo_]

        cur = lo_
        for t in reversed(tables):
            cand = t[cur]
            cur = jnp.where(cand < hi_, cand, cur)
        # level 0 last and CURRENT: guarantees one-step progress per
        # live slot even right after a displacement spawn
        cand = newP[cur]
        cur = jnp.where(cand < hi_, cand, cur)
        became_loop = cur == hi_
        climb_lo = jnp.where(became_loop, n, cur)
        climb_hi = jnp.where(became_loop, n, hi_)

        retire = hi_ == now
        displaced = retire & (now < old_at_lo) & (old_at_lo < n)
        out_lo = jnp.where(retire,
                           jnp.where(displaced, now, n),
                           climb_lo).astype(jnp.int32)
        out_hi = jnp.where(retire,
                           jnp.where(displaced, old_at_lo, n),
                           climb_hi).astype(jnp.int32)
        changed = jnp.any((out_lo != lo_) | (out_hi != hi_))
        return out_lo, out_hi, newP, changed, rounds + 1

    return body


@partial(jax.jit, static_argnames=("n", "lift_levels", "segment_rounds"))
def fold_segment_pos_hoisted(
    P: jax.Array,
    loP: jax.Array,
    hiP: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 32,
):
    """:func:`fold_segment_pos` (exact descent) with the lifting-table
    stack HOISTED out of the round loop: tables t_1..t_{L-1} are built
    once from the entry table and stay fixed for the whole segment;
    only level 0 (the table itself) is current inside rounds. Same
    (loP, hiP, P, stats) contract. The final forest is the same unique
    fixpoint (stale jumps are sound, see :func:`_pos_round_body_stale`);
    per-round trajectories may differ from the fresh-table body, so the
    adaptive driver treats round counts as diagnostics, not contracts.

    Fixpoint-exit soundness: the driver loop only stops on a segment
    reporting no change, and every segment starts with tables freshly
    built from its entry table — a first round that changes nothing ran
    with a fully-current view, so 'no change' is a genuine fixpoint."""
    return fold_segment_pos_stale(P, loP, hiP,
                                  build_lift_tables(P, n, lift_levels),
                                  n, segment_rounds=segment_rounds)


@partial(jax.jit, static_argnames=("n", "lift_levels"))
def build_lift_tables(P: jax.Array, n: int, lift_levels: int = 0):
    """The exact-descent lifting stack t_1..t_{L-1} as a standalone
    program, for CROSS-SEGMENT reuse (``stale_reuse`` > 1 in the
    adaptive driver): (L-1) x V squaring gathers once per rebuild
    instead of once per segment."""
    lift_levels, _ = _resolve(n, lift_levels, "exact")
    t = P.astype(jnp.int32)
    tables = []
    for _ in range(lift_levels - 1):
        t = t[t]
        tables.append(t)
    return tuple(tables)


@partial(jax.jit, static_argnames=("n", "segment_rounds"))
def fold_segment_pos_stale(
    P: jax.Array,
    loP: jax.Array,
    hiP: jax.Array,
    tables: tuple,
    n: int,
    segment_rounds: int = 32,
):
    """:func:`fold_segment_pos_hoisted` with the stack passed IN
    (:func:`build_lift_tables`) so the driver can reuse it across
    several segments. Soundness is the stronger form the stale round
    body already satisfies: level 0 is always current (one-step
    progress per live slot, so no livelock — a constraint whose level-0
    jump is inadmissible retires by scatter-min within two rounds), and
    a no-change segment is a genuine fixpoint REGARDLESS of stack
    freshness, because slots only change toward progress and the table
    only changes through a retiring slot (see _pos_round_body). Stale
    jumps land on genuine ancestors (permanence), so the unique
    fixpoint is unchanged; only round counts differ."""
    body = _pos_round_body_stale(n, tuple(tables))
    return _run_segment(body, P, loP, hiP, n, segment_rounds)


@partial(jax.jit, static_argnames=("n", "lift_levels", "segment_rounds",
                                   "descent"))
def fold_segment_pos(
    P: jax.Array,
    loP: jax.Array,
    hiP: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 32,
    descent: str = "auto",
):
    """At most ``segment_rounds`` rounds in ONE device execution, entirely
    in position space — the production hot path (no pos/order tables in
    the compiled program at all). Returns (loP, hiP, P, stats) where
    ``stats`` is int32[3] = (changed, rounds, live): packing the three
    control scalars into one vector lets the host driver read them with
    a SINGLE device pull per segment — each pull is a full round-trip
    (~73 ms on the tunneled bench chip), and the driver needs all three
    every segment. Bounding rounds per execution keeps accelerator calls
    short (long single executions tripped the TPU worker watchdog in
    round 2's first bench attempt)."""
    lift_levels, descent = _resolve(n, lift_levels, descent)
    body = _pos_round_body(n, lift_levels, descent)
    return _run_segment(body, P, loP, hiP, n, segment_rounds)


def _pos_small_round_body(n: int, jumps: int):
    """Jump-mode round body for SMALL active buffers: identical
    retire/displace semantics to :func:`_pos_round_body`, but the climb is
    ``jumps`` single parent steps via per-element gathers — O(C') work per
    round with NO O(V) lifting-table rebuild. Used for the fixpoint tail,
    where a handful of displacement-chain constraints would otherwise pay
    the full-buffer, full-table cost every round."""

    def body(state):
        lo_, hi_, P_, _, rounds = state
        old_at_lo = P_[lo_]
        newP = P_.at[lo_].min(hi_, mode="drop")
        now = newP[lo_]

        cur = lo_
        for _ in range(jumps):
            cand = newP[cur]
            cur = jnp.where(cand < hi_, cand, cur)
        became_loop = cur == hi_
        climb_lo = jnp.where(became_loop, n, cur)
        climb_hi = jnp.where(became_loop, n, hi_)

        retire = hi_ == now
        displaced = retire & (now < old_at_lo) & (old_at_lo < n)
        out_lo = jnp.where(retire,
                           jnp.where(displaced, now, n),
                           climb_lo).astype(jnp.int32)
        out_hi = jnp.where(retire,
                           jnp.where(displaced, old_at_lo, n),
                           climb_hi).astype(jnp.int32)
        changed = jnp.any((out_lo != lo_) | (out_hi != hi_))
        return out_lo, out_hi, newP, changed, rounds + 1

    return body


@partial(jax.jit, static_argnames=("n", "jumps", "segment_rounds"))
def fold_segment_small_pos(
    P: jax.Array,
    loP: jax.Array,
    hiP: jax.Array,
    n: int,
    jumps: int = 8,
    segment_rounds: int = 64,
):
    """Bounded segment of jump-mode rounds (see _pos_small_round_body).
    Same (loP, hiP, P, stats) contract as :func:`fold_segment_pos`."""
    body = _pos_small_round_body(n, jumps)
    return _run_segment(body, P, loP, hiP, n, segment_rounds)


# ---------------------------------------------------------------------------
# batched segment dispatch (ISSUE 1 tentpole): fold N staged streaming
# segments inside ONE bounded device program. The per-segment driver
# above pays one host round-trip (the sv pull) per bounded segment —
# measured as the dominant build cost through a degraded link (~160 s of
# the 227.8 s round-5 build against a 68 s device floor, VERDICT r5
# item 2). Here the host stages N segments as padded [N, C] position
# blocks, the device runs an outer while_loop that advances segment by
# segment (each segment's rounds are the SAME _pos_round_body), and the
# host pulls one packed stats word per execution: O(segments / N) syncs
# instead of O(segments). The forest is bit-identical — the elimination
# fixpoint is unique given the constraint multiset, independent of how
# the segments are scheduled (tests/test_dispatch_batch.py).
# ---------------------------------------------------------------------------

def batch_segment_fixpoint(
    P: jax.Array,
    loB: jax.Array,
    hiB: jax.Array,
    n: int,
    lift_levels: int = 0,
    descent: str = "auto",
    batch_rounds: int = 0,
):
    """Traceable core of the batched dispatch: advance through the rows
    of the [N, C] active blocks, one fixpoint round per loop step, with
    on-device stop conditions — a segment is done when a round changes
    nothing (the genuine fixpoint, see :func:`_pos_round_body`), the
    program exits when every segment is done or ``batch_rounds`` total
    rounds are spent (watchdog bounding; the host re-dispatches on the
    returned blocks to resume). A converged segment's row is stored
    all-sentinel — its residual live slots are implied by the table —
    so re-entry after a budget exhaustion re-confirms it in one round.

    Returns ``(loB, hiB, P, sv)`` with ``sv`` int32[4] =
    (segments_done, rounds, live, retired) — ONE packed stats word per
    batch. Callable directly under shard_map (the sharded pipeline's
    per-device form); :func:`fold_segments_batch_pos` is the jitted
    single-device entry."""
    N, _ = loB.shape
    lift_levels, descent = _resolve(n, lift_levels, descent)
    if batch_rounds <= 0:
        batch_rounds = 32 * N
    round_body = _pos_round_body(n, lift_levels, descent)
    # derive carried scalars from the block so their sharding/varying
    # axes match the loop outputs (required under shard_map, as in
    # _init_state)
    zero = (loB[0, 0] * 0).astype(jnp.int32)
    dummy_changed = loB[0, 0] == loB[0, 0]

    def load(block, i):
        return lax.dynamic_index_in_dim(block, i, axis=0, keepdims=False)

    def cond(state):
        i, _, _, _, _, _, rounds, _ = state
        return (i < N) & (rounds < batch_rounds)

    def body(state):
        i, lo, hi, loB_, hiB_, P_, rounds, retired = state
        lo2, hi2, P2, changed, _ = round_body(
            (lo, hi, P_, dummy_changed, zero))
        retired = retired + jnp.sum((lo2 == n) & (lo != n),
                                    dtype=jnp.int32)
        seg_done = ~changed
        sent = jnp.full_like(lo2, n)
        # store the working buffer back every round so the blocks always
        # reflect resumable state when the round budget exhausts
        loB_ = lax.dynamic_update_index_in_dim(
            loB_, jnp.where(seg_done, sent, lo2), i, axis=0)
        hiB_ = lax.dynamic_update_index_in_dim(
            hiB_, jnp.where(seg_done, sent, hi2), i, axis=0)
        i2 = jnp.where(seg_done, i + 1, i)
        nxt = jnp.minimum(i2, N - 1)
        lo3 = jnp.where(seg_done, load(loB_, nxt), lo2)
        hi3 = jnp.where(seg_done, load(hiB_, nxt), hi2)
        return (i2, lo3, hi3, loB_, hiB_, P2, rounds + 1, retired)

    state = (zero, load(loB, zero), load(hiB, zero), loB, hiB,
             P.astype(jnp.int32), zero, zero)
    i_f, _, _, loB_f, hiB_f, P_f, rounds_f, retired_f = lax.while_loop(
        cond, body, state)
    live = jnp.sum(loB_f != n, dtype=jnp.int32)
    sv = jnp.stack([i_f, rounds_f, live, retired_f])
    return loB_f, hiB_f, P_f, sv


@partial(jax.jit, static_argnames=("n", "lift_levels", "descent",
                                   "batch_rounds"))
def fold_segments_batch_pos(
    P: jax.Array,
    loB: jax.Array,
    hiB: jax.Array,
    n: int,
    lift_levels: int = 0,
    descent: str = "auto",
    batch_rounds: int = 0,
):
    """Jitted :func:`batch_segment_fixpoint` — the single-device batched
    dispatch program."""
    return batch_segment_fixpoint(P, loB, hiB, n, lift_levels=lift_levels,
                                  descent=descent,
                                  batch_rounds=batch_rounds)


@partial(jax.jit, static_argnames=("n", "lift_levels", "descent",
                                   "batch_rounds"), donate_argnums=(0, 1, 2))
def fold_segments_batch_pos_donated(
    P: jax.Array,
    loB: jax.Array,
    hiB: jax.Array,
    n: int,
    lift_levels: int = 0,
    descent: str = "auto",
    batch_rounds: int = 0,
):
    """:func:`fold_segments_batch_pos` with the carried table and the
    [N, C] staging blocks DONATED: XLA reuses their HBM buffers for the
    execution's outputs instead of allocating a second copy of each,
    so a chain of executions holds one table + one staging block per
    in-flight execution rather than two (ISSUE 4 tentpole;
    utils/membudget.build_phase_bytes models the credit). Inputs are
    INVALIDATED by the call — only for callers that rebind, like the
    re-dispatch loops here."""
    return batch_segment_fixpoint(P, loB, hiB, n, lift_levels=lift_levels,
                                  descent=descent,
                                  batch_rounds=batch_rounds)


@partial(jax.jit, static_argnames=("n",))
def orient_chunks_batch_pos(chunks: jax.Array, pos: jax.Array, n: int):
    """(N, C, 2) stacked padded chunks -> oriented POSITION blocks
    (loB, hiB), each row an independent [C] active buffer — the [N, C]
    staging block of the batched dispatch. Sentinel-padded rows (and the
    per-chunk padding tail) orient to the inert (n, n), which is the
    per-segment live mask: a fully-inert row converges in one round."""
    return jax.vmap(lambda c: orient_edges_pos(c, pos, n))(chunks)



def _resolve_batch_rounds(batch_rounds: int, segment_rounds: int,
                          N: int) -> int:
    """Per-execution round budget of the batched dispatch: default
    ``segment_rounds * N`` (the allowance the per-segment driver would
    spread over N syncs). Every execution restarts the segment cursor
    at 0, and each already-converged segment still costs one
    confirmation round: a per-execution budget below N can stall the
    cursor at the same prefix forever and silently return an
    unconverged forest at the max_rounds backstop — clamp so one
    execution can always cross the whole block."""
    if batch_rounds <= 0:
        batch_rounds = max(1, segment_rounds) * max(N, 1)
    return max(batch_rounds, max(N, 1))


def _t_ms(stats: dict, key: str, dt_s: float) -> None:
    """Accumulate a millisecond counter UNROUNDED (same rule as t_add:
    consumers round at read time so sums never drift past the wall)."""
    stats[key] = stats.get(key, 0.0) + dt_s * 1e3


def _seed_ms_counters(stats: dict) -> None:
    """Pre-seed the overlap counters so every driver run emits all of
    them — a fold that converges before its second execution would
    otherwise never touch ``device_gap_ms``, and the bench contract /
    regression gate treat a missing field as incomparable rather than
    zero. The H2D ingest pair (ISSUE 12) seeds here too: a
    device-stream build stages nothing, and its 0.0s are the
    zero-host-bytes evidence, not an absent measurement."""
    stats.setdefault("host_blocked_ms", 0.0)
    stats.setdefault("device_gap_ms", 0.0)
    stats.setdefault("h2d_staged_ms", 0.0)
    stats.setdefault("h2d_blocked_ms", 0.0)


def fold_segments_batch(
    P: jax.Array,
    loB: jax.Array,
    hiB: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 2,
    descent: str = "auto",
    batch_rounds: int = 0,
    max_rounds: int = 1 << 20,
    stats=None,
    donate: bool = False,
):
    """SYNCHRONOUS host driver of the batched dispatch over ONE staged
    block: loop bounded :func:`fold_segments_batch_pos` executions
    until every staged segment reports done — ONE packed-stats pull
    per EXECUTION instead of per segment. The default per-execution
    round budget is ``segment_rounds * N`` (see
    :func:`_resolve_batch_rounds`), so the host sync count drops by ~N
    while no single device execution runs longer than N bounded
    segments back to back (the watchdog envelope scales with the
    staged batch, not with the stream). Returns ``(P, total_rounds)``.

    ``donate`` runs the donated program
    (:func:`fold_segments_batch_pos_donated`): the caller's P/loB/hiB
    are INVALIDATED.

    Implemented as :func:`fold_segments_pipelined` at depth 1 over the
    single block — the pipelined driver's documented degenerate mode
    (same executions in the same order, pinned by
    tests/test_inflight.py) — so there is exactly one dispatch loop to
    maintain. ``host_blocked_ms``/``device_gap_ms`` quantify the
    alternation tax deeper pipelines remove; on the max_rounds
    backstop, ``batch_incomplete_segments`` flags the undrained block
    (key presence is the contract)."""
    return fold_segments_pipelined(
        P, iter([(loB, hiB)]), n, inflight=1, lift_levels=lift_levels,
        segment_rounds=segment_rounds, descent=descent,
        batch_rounds=batch_rounds, max_rounds=max_rounds, donate=donate,
        stats=stats)


# ---------------------------------------------------------------------------
# asynchronous in-flight dispatch pipeline (ISSUE 4 tentpole). The batched
# driver above is still a synchronous lockstep: stage -> execute ->
# BLOCKING packed-stats pull -> decide -> stage next, so the device idles
# through every host read/orient/pad and the host idles through every
# device program. JAX arrays are futures, so the pull is the only forced
# sync — this driver keeps a bounded FIFO (depth D) of issued executions
# whose stats words stay un-pulled, chains each new execution on the
# previous one's (async) output table, and converts sv to host ints
# one-behind. Buffers are donated along the chain, so the staged blocks
# and the carried table are REUSED across executions instead of doubling
# peak HBM (fold_segments_batch_pos_donated).
#
# Speculation + bit-identity: a new staged group is issued assuming the
# executions ahead of it drain their blocks (the common case — the
# per-execution round budget covers the whole block). When a pulled sv
# reveals an execution did NOT drain (budget exhaustion), its leftover
# blocks are re-queued and re-dispatched on the CURRENT chain table;
# that re-orders constraint resolution but cannot change the result,
# because the elimination fixpoint is the unique forest of the inserted
# constraint multiset, independent of fold order (the PR-1 argument, now
# applied across groups instead of within one). At stream end the driver
# speculates the other way — "the last blocks have NOT converged" — and
# issues their re-dispatch before pulling; if the pull says converged,
# the speculative executions are DISCARDED: their svs are never read
# (zero extra syncs) and their output table is the bit-identical
# re-confirmation of the converged one (drained blocks are all-sentinel;
# re-entry re-confirms each row in one round and leaves the table
# untouched), so adopting it IS resuming from the last confirmed carry.
# ---------------------------------------------------------------------------

def fold_segments_pipelined(
    P: jax.Array,
    staged,
    n: int,
    inflight: int = 2,
    lift_levels: int = 0,
    segment_rounds: int = 2,
    descent: str = "auto",
    batch_rounds: int = 0,
    max_rounds: int = 1 << 20,
    donate: bool = True,
    stats=None,
    on_confirm=None,
    on_flush=None,
):
    """Fold a stream of staged [N, C] oriented position blocks with up
    to ``inflight`` device executions in flight (see the block comment
    above for the speculation/discard model).

    ``staged`` yields ``(loB, hiB)`` or ``(loB, hiB, tag)`` blocks
    (:func:`orient_chunks_batch_pos`); blocks are consumed (donated when
    ``donate``). ``on_confirm(tag, rounds, P)`` fires after each stats
    pull — ``tag`` is the staged group's tag for the first execution of
    a group and None for re-dispatches — with the CURRENT chain-tip
    table (an async jax array valid until the next execution is issued;
    read it immediately, do not store it). A truthy return from
    ``on_confirm`` requests a FLUSH BARRIER: the driver stops consuming
    new groups, drains everything already issued (including leftover
    re-dispatches) to completion, then calls ``on_flush(P)`` with a
    table that provably contains the full constraint multiset of every
    confirmed group — the only place a checkpoint cut is sound, because
    mid-pipeline the tip table can UNDER-represent a confirmed group
    whose budget-exhausted leftovers are still queued host-side.
    Returns ``(P, total_rounds)``; ``inflight=1`` degenerates to the
    synchronous execute/pull/decide loop (same executions in the same
    order as :func:`fold_segments_batch` over the group sequence).

    Counters (all absorbed by the obs tracer at span boundaries and
    emitted as bench contract fields): ``host_blocked_ms`` = wall spent
    inside blocking sv pulls; ``device_gap_ms`` = wall from a pull that
    EMPTIED the in-flight queue to the next execution's dispatch (the
    device provably idles through exactly those windows; with D >= 2
    the queue rarely empties and the counter collapses toward 0);
    ``inflight_discards`` = speculative executions whose sv was never
    read. ``max_rounds`` is a backstop, not an exact cap: in-flight
    executions are drained and counted when it trips, and
    ``batch_incomplete_segments`` then reports the staged BLOCKS known
    undrained — a LOWER BOUND: the unconsumed remainder of the stream
    is never staged (counting it would force its H2D uploads), so the
    flag's presence, not its magnitude, is the incompleteness
    contract (as in :func:`fold_segments_batch`)."""
    from collections import deque

    from sheep_tpu.utils import fault

    if inflight < 1:
        raise ValueError("inflight must be >= 1")
    if stats is None:
        stats = {}
    _seed_ms_counters(stats)
    stats.setdefault("inflight_discards", 0)
    fold = fold_segments_batch_pos_donated if donate \
        else fold_segments_batch_pos
    fifo: deque = deque()       # issued, un-pulled executions, FIFO
    leftovers: deque = deque()  # blocks of partially-drained executions
    it = iter(staged)
    t_start = time.perf_counter()

    def pull_group():
        try:
            return next(it)
        except StopIteration:
            return None

    state = {"tipP": P.astype(jnp.int32), "tip": None, "idle_since": None,
             "flushing": False}
    nxt = pull_group()
    total = 0

    def issue(loB, hiB, kind, tag):
        now = time.perf_counter()
        if state["idle_since"] is not None:
            _t_ms(stats, "device_gap_ms", now - state["idle_since"])
            state["idle_since"] = None
        # dispatch-time injection point (ISSUE 9): a fault raised here
        # unwinds the whole driver with the chain un-drained — exactly
        # what a real allocation failure inside fold() does — so the
        # backend-level retry/degrade wrapper sees the production shape
        state["issued"] = state.get("issued", 0) + 1
        fault.maybe_fail("dispatch", state["issued"],
                         kinds=("oom", "device"))
        N = int(loB.shape[0])
        prevP = state["tipP"]
        lo2, hi2, P2, sv = fold(
            prevP, loB, hiB, n, lift_levels=lift_levels,
            descent=descent,
            batch_rounds=_resolve_batch_rounds(batch_rounds,
                                               segment_rounds, N))
        if donate:
            # SHEEP_SANITIZE: the chained inputs must really be
            # poisoned — a silently ignored donation doubles HBM and
            # leaves use-after-donate bugs latent. Touches only
            # is_deleted metadata, never the dead buffers' contents:
            sanitize.check_donated(
                prevP, loB, hiB,  # sheeplint: donate-ok
                origin="fold_segments_batch_pos_donated")
        state["tipP"] = P2
        rec = {"lo": lo2, "hi": hi2, "sv": sv, "kind": kind, "tag": tag,
               "N": N}
        state["tip"] = rec
        fifo.append(rec)

    def confirm(rec):
        """Blocking pull of one execution's stats word; returns done."""
        nonlocal total
        t_pull = time.perf_counter()
        # the ONE designed sync of the pipeline: the one-behind packed
        # stats pull (everything else stays an unread future)
        with sanitize.sync_ok("pipelined-sv-pull"):
            done, r, live, retired = \
                (int(x) for x in np.asarray(rec["sv"]))  # sheeplint: sync-ok
        now = time.perf_counter()
        _t_ms(stats, "host_blocked_ms", now - t_pull)
        stats["host_syncs"] = stats.get("host_syncs", 0) + 1
        stats["batch_execs"] = stats.get("batch_execs", 0) + 1
        stats["batch_retired"] = stats.get("batch_retired", 0) + retired
        stats["device_rounds"] = stats.get("device_rounds", 0) + r
        total += r
        if not fifo:
            # nothing left in flight: the device finished this execution
            # no later than the pull completed and idles until the next
            # dispatch
            state["idle_since"] = now
        drained = done >= rec["N"]
        if drained:
            # any speculative re-dispatches of these (now known-drained)
            # blocks are bit-identical re-confirmations: discard them —
            # never read their svs — and let the chain tip (their
            # output) stand in for the confirmed carry
            while fifo and fifo[0]["kind"] == "spec":
                fifo.popleft()
                stats["inflight_discards"] = \
                    stats.get("inflight_discards", 0) + 1
            if not fifo:
                state["idle_since"] = time.perf_counter()
        elif not (fifo and fifo[0]["kind"] == "spec"):
            # budget exhausted and no speculative continuation already
            # in flight: the leftover constraints live in this
            # execution's output blocks — re-queue them (re-dispatching
            # on the current chain table is sound: the fixpoint is
            # order-independent in the constraint multiset)
            leftovers.append((rec["lo"], rec["hi"]))
        if on_confirm is not None:
            if on_confirm(rec["tag"] if rec["kind"] == "group" else None,
                          r, state["tipP"]):
                state["flushing"] = True
        return drained

    # SHEEP_SANITIZE: arm the stray-sync traps for the whole dispatch
    # chain — between the annotated pulls, every device value must
    # stay an unread future (one stray int()/bool() here silently
    # reverts the pipeline to lockstep; the sanitizer makes it raise)
    with sanitize.guard("dispatch"):
        while True:
            while len(fifo) < inflight:
                if leftovers:
                    lo, hi = leftovers.popleft()
                    issue(lo, hi, "left", None)
                elif state["flushing"]:
                    # flush barrier: no new groups, no speculation —
                    # only drain what is already in flight
                    break
                elif nxt is not None:
                    lo, hi = nxt[0], nxt[1]
                    tag = nxt[2] if len(nxt) > 2 else None
                    # dispatch the staged group BEFORE pulling the next
                    # one: pull_group() can block on the producer's
                    # read/pad (prefetch queue empty on IO-bound
                    # streams), and the device should be folding
                    # through that wall, not waiting behind it
                    issue(lo, hi, "group", tag)
                    nxt = pull_group()
                elif fifo:
                    # stream drained, queue not full: speculate the
                    # newest execution does NOT finish its blocks and
                    # issue its re-dispatch now (discarded unread if
                    # it did)
                    tip = state["tip"]
                    issue(tip["lo"], tip["hi"], "spec", None)
                else:
                    break
            if not fifo:
                if state["flushing"]:
                    # fully drained (the fill loop always re-issues
                    # leftovers before this point): every confirmed
                    # group's constraints are in the tip table — the
                    # sound cut
                    state["flushing"] = False
                    if on_flush is not None:
                        on_flush(state["tipP"])
                    if nxt is not None:
                        continue
                break
            confirm(fifo.popleft())
            if total >= max_rounds:
                # backstop: drain what is already in flight (those
                # rounds ran — counting them keeps the stats honest),
                # then report the undrained remainder instead of
                # exiting silently. A flush barrier requested during
                # this drain is deliberately DROPPED: with leftovers
                # pending there is no sound cut to save, and the run
                # is returning incomplete (and flagged) anyway —
                # resume simply redoes from the previous barrier
                while fifo:
                    confirm(fifo.popleft())
                pending = len(leftovers) + (1 if nxt is not None else 0)
                if pending:
                    stats["batch_incomplete_segments"] = pending
                break
    stats["t_batch_s"] = stats.get("t_batch_s", 0.0) + \
        (time.perf_counter() - t_start)
    return state["tipP"], total


# ---------------------------------------------------------------------------
# sort-merge round prototype (VERDICT r2 item 2): the one primitive class
# not yet tried as the round body. Replaces every random C-from-V table
# gather with a sort-based join so the round rides lax.sort throughput
# instead of the ~100-150 M elem/s XLA gather roofline. Kept bit-identical
# to the jump-mode round (tests/test_tpu_ops.py) so the keep/reject
# decision is purely a measured-throughput question — see BASELINE.md
# "sort-based round" entry for the measured verdict.
# ---------------------------------------------------------------------------

def sorted_lookup(tables, idx: jax.Array, n: int):
    """``[t[idx] for t in tables]`` with NO random gather.

    Mechanism: concatenate the dense key range [0, n] (carrying each
    table's values) with the query indices, one lexicographic
    ``lax.sort`` by (key, is_query) — every query row lands immediately
    after the table row with its key, table keys being dense — then a
    last-valid ``associative_scan`` propagates table values onto query
    rows, and one scatter returns results to slot order. Cost:
    O((V + C) log) sort + streaming scan, vs C random gathers; wins iff
    sort throughput/element beats the gather roofline on the target
    device (the microbench probes exactly this pair)."""
    C = idx.shape[0]
    m = n + 1
    keys = jnp.concatenate([jnp.arange(m, dtype=jnp.int32),
                            idx.astype(jnp.int32)])
    tag = jnp.concatenate([jnp.zeros(m, jnp.int32), jnp.ones(C, jnp.int32)])
    slot = jnp.concatenate([jnp.zeros(m, jnp.int32),
                            jnp.arange(C, dtype=jnp.int32)])
    payloads = tuple(jnp.concatenate([t.astype(jnp.int32),
                                      jnp.zeros(C, jnp.int32)])
                     for t in tables)
    srt = lax.sort((keys, tag, slot) + payloads, num_keys=2)
    st, ss, sp = srt[1], srt[2], srt[3:]
    is_table = st == 0

    def combine(a, b):
        # last-valid: b's payloads win wherever b is a table row
        vals = tuple(jnp.where(b[-1], pb, pa)
                     for pa, pb in zip(a[:-1], b[:-1]))
        return vals + (a[-1] | b[-1],)

    scanned = lax.associative_scan(combine, sp + (is_table,))
    # scatter query rows back to slot order; table rows go to a dump slot
    dump = jnp.where(st == 1, ss, C)
    out = []
    for v in scanned[:-1]:
        buf = jnp.zeros(C + 1, jnp.int32).at[dump].set(v, mode="drop")
        out.append(buf[:C])
    return out


def _pos_sortmerge_round_body(n: int, jumps: int):
    """Jump-mode round with every table *read* through
    :func:`sorted_lookup` — identical retire/displace/climb semantics to
    :func:`_pos_small_round_body` (the scatter-min write stays a
    scatter; it is not the dominant cost and has no sort equivalent
    cheaper than a segmented reduce of the same sorted buffer)."""

    def body(state):
        lo_, hi_, P_, _, rounds = state
        newP = P_.at[lo_].min(hi_, mode="drop")
        old_at_lo, now = sorted_lookup((P_, newP), lo_, n)

        cur = lo_
        for _ in range(jumps):
            cand = sorted_lookup((newP,), cur, n)[0]
            cur = jnp.where(cand < hi_, cand, cur)
        became_loop = cur == hi_
        climb_lo = jnp.where(became_loop, n, cur)
        climb_hi = jnp.where(became_loop, n, hi_)

        retire = hi_ == now
        displaced = retire & (now < old_at_lo) & (old_at_lo < n)
        out_lo = jnp.where(retire,
                           jnp.where(displaced, now, n),
                           climb_lo).astype(jnp.int32)
        out_hi = jnp.where(retire,
                           jnp.where(displaced, old_at_lo, n),
                           climb_hi).astype(jnp.int32)
        changed = jnp.any((out_lo != lo_) | (out_hi != hi_))
        return out_lo, out_hi, newP, changed, rounds + 1

    return body


@partial(jax.jit, static_argnames=("n", "jumps", "segment_rounds"))
def fold_segment_sortmerge_pos(
    P: jax.Array,
    loP: jax.Array,
    hiP: jax.Array,
    n: int,
    jumps: int = 8,
    segment_rounds: int = 64,
):
    """Sort-merge variant of :func:`fold_segment_small_pos` — same
    (loP, hiP, P, stats) contract, bit-identical trajectories (asserted
    by tests), different primitive mix for the microbench decision."""
    body = _pos_sortmerge_round_body(n, jumps)
    return _run_segment(body, P, loP, hiP, n, segment_rounds)


@partial(jax.jit, static_argnames=("n", "lift_levels", "max_rounds", "descent"))
def fold_edges(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    max_rounds: int = 1 << 20,
    descent: str = "auto",
):
    """Fold active constraints (lo, hi) into the carried forest table.

    Returns (minp int32[n+1], rounds int32); minp[x] = elimination
    position of x's parent (n = root/no parent). The active buffer is
    fixed-size: a retiring slot is reused in place by the constraint it
    displaces, so per-round work is O(len(lo)), independent of V.

    Vertex-space contract over the position-space core: inputs convert
    with three gathers (minp[order], pos[lo], pos[hi]), the result with
    one (P[pos]) — exact integer permutations, so results are identical.

    ``lift_levels`` = number of doubled ancestor tables per round
    (0 -> auto: ceil(log2(n+1)), enough to cover any chain in one round).
    ``descent`` = "exact" | "stream" | "auto" (see module docstring).
    """
    lift_levels, descent = _resolve(n, lift_levels, descent)
    body = _pos_round_body(n, lift_levels, descent)

    def cond(state):
        _, _, _, changed, rounds = state
        return changed & (rounds < max_rounds)

    state = _init_state(minp[order], pos[lo], pos[hi])
    _, _, P_f, _, rounds = lax.while_loop(cond, body, state)
    return P_f[pos], rounds


@partial(jax.jit, static_argnames=("n", "lift_levels", "segment_rounds",
                                   "descent"))
def fold_edges_segment(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 32,
    descent: str = "auto",
):
    """Vertex-space wrapper of :func:`fold_segment_pos` (same state
    contract as before: returns (lo, hi, minp, changed, rounds) with
    vertex ids). The round dynamics commute with the pos/order
    permutation, so the returned state is bit-identical to the historic
    vertex-space implementation."""
    lift_levels, descent = _resolve(n, lift_levels, descent)
    body = _pos_round_body(n, lift_levels, descent)

    def cond(state):
        _, _, _, changed, rounds = state
        return changed & (rounds < segment_rounds)

    state = _init_state(minp[order], pos[lo], pos[hi])
    loP, hiP, P_f, changed, rounds = lax.while_loop(cond, body, state)
    return order[loP], order[hiP], P_f[pos], changed, rounds




@partial(jax.jit, static_argnames=("n", "size", "dedup"))
def compact_actives(lo: jax.Array, hi: jax.Array, n: int, size: int,
                    dedup: bool = False):
    """Pack the live constraints into a (size,) buffer, padding with the
    inert sentinel (n, n). Valid only when the live count <= size (the
    caller checks); slot identity is meaningless — only the SET of
    active constraints matters to the fixpoint (duplicates retire
    together and spawn identical displacements), so compaction and
    dedup are exact.

    ``dedup`` additionally drops duplicate (lo, hi) pairs first via one
    two-key sort: after a few rounds many slots have been rewritten to
    the same (ancestor, hi) constraint. The production driver sizes the
    target from the cheap pre-dedup live count, which every segment
    program returns in its packed stats vector (:func:`fold_segment_pos`)
    — a per-segment distinct count would cost a full-buffer sort each
    segment (measured: seconds at C=2^24 on the v5e). The live count is
    an upper bound on the distinct count, so the size is always
    sufficient. :func:`count_live_distinct` exists for
    diagnostics/tests."""
    if dedup:
        lo, hi = lax.sort((lo, hi), num_keys=2)
        dup = (lo == jnp.roll(lo, 1)) & (hi == jnp.roll(hi, 1))
        dup = dup.at[0].set(False)
        lo = jnp.where(dup, n, lo)
        hi = jnp.where(dup, n, hi)
    c = lo.shape[0]
    # fill slots index an appended sentinel row, so padding is inert
    sel = jnp.nonzero(lo != n, size=size, fill_value=c)[0]
    lo_ext = jnp.concatenate([lo, jnp.full(1, n, lo.dtype)])
    hi_ext = jnp.concatenate([hi, jnp.full(1, n, hi.dtype)])
    return lo_ext[sel], hi_ext[sel]


@partial(jax.jit, static_argnames=("n",))
def count_live_distinct(lo: jax.Array, hi: jax.Array, n: int):
    slo, shi = lax.sort((lo, hi), num_keys=2)
    dup = (slo == jnp.roll(slo, 1)) & (shi == jnp.roll(shi, 1))
    dup = dup.at[0].set(False)
    live = jnp.sum(slo != n)
    return live, live - jnp.sum(dup & (slo != n))




def _order_host(pos_host, n: int):
    """Inverse permutation of pos_host with the sentinel slot appended."""
    order_host = np.empty(n + 1, dtype=np.int64)
    order_host[np.asarray(pos_host)] = np.arange(n, dtype=np.int64)
    order_host[n] = n
    return order_host


def _host_tail_finish_pos(P, loP, hiP, n: int, size: int, pos_host):
    """Finish the fixpoint on HOST via the native core's Liu pass.

    The fixpoint tail is a displacement cascade — inherently sequential
    pointer-chasing that a vector machine resolves one link per round
    (measured: 6.8k tail rounds at RMAT-20 streamed in 4 chunks). The
    native C++ insertion resolves the whole cascade in O(total chain
    length) on host, so once the live count is small we pull the O(V)
    table + the compacted live constraints, extend the forest there, and
    push the table back. Same unique forest (cross-backend bit-identity
    is an existing test invariant)."""
    from sheep_tpu.core import native

    clo, chi = compact_actives(loP, hiP, n, size, dedup=True)
    # designed host-tail handoff: the compacted live constraints and
    # the O(V) table cross to the host exactly once per tail
    with sanitize.sync_ok("host-tail-pull"):
        lo_np = np.asarray(clo)  # sheeplint: sync-ok
        hi_np = np.asarray(chi)  # sheeplint: sync-ok
    mask = lo_np != n
    pos_host = np.asarray(pos_host)
    order_host = _order_host(pos_host, n)
    edges = np.stack([order_host[lo_np[mask]], order_host[hi_np[mask]]],
                     axis=1)
    P_np = np.asarray(P)  # the one O(V) device->host pull
    pp = P_np[pos_host]   # vertex-indexed parent positions
    parent = np.where(pp < n, order_host[np.minimum(pp, n)],
                      NO_PARENT).astype(np.int64)
    parent = native.build_elim_tree(edges, pos_host, parent)
    newP = np.full(n + 1, n, dtype=np.int32)
    has = parent >= 0
    newP[pos_host[has]] = pos_host[parent[has]]
    return jnp.asarray(newP)


def host_tail_delta(P_snap, loP, hiP, n: int, pos_host):
    """Resolve a compacted fixpoint tail on HOST and return it as DELTA
    constraints instead of a replacement table.

    Same native Liu pass as :func:`_host_tail_finish_pos`, but the result
    is the set of (position, new_parent_position) pairs whose parent
    CHANGED — exactly the tree edges the resolution added. Injecting
    those pairs as ordinary actives into any later fold yields the same
    unique fixpoint (the forest is a function of the inserted constraint
    multiset; a resolved link is a derived tree edge of a sub-multiset,
    which is what :func:`merge_forests` folds), so the caller can run
    the native pass in a worker thread while the device folds the next
    chunk, and ship an O(changed) delta instead of the O(V) table push.

    Inputs must be HOST-safe snapshots (jax arrays are immutable, so the
    device arrays themselves are safe); everything here is numpy + the
    native core — no jax dispatch — making it executor-thread-friendly
    apart from the initial np.asarray pulls."""
    from sheep_tpu.core import native

    lo_np = np.asarray(loP)
    hi_np = np.asarray(hiP)
    mask = lo_np != n
    pos_host = np.asarray(pos_host)
    order_host = _order_host(pos_host, n)
    edges = np.stack([order_host[lo_np[mask]], order_host[hi_np[mask]]],
                     axis=1)
    P_np = np.asarray(P_snap)  # O(V) pull overlapped with device work
    pp = P_np[pos_host]
    parent = np.where(pp < n, order_host[np.minimum(pp, n)],
                      NO_PARENT).astype(np.int64)
    # native.build_elim_tree writes into a contiguous int64 parent array
    # IN PLACE (and returns it) — diff against a snapshot, not the alias
    new_parent = native.build_elim_tree(edges, pos_host, parent.copy())
    ch = np.nonzero(new_parent != parent)[0]
    # links are only ever added or improved, never removed
    assert len(ch) == 0 or new_parent[ch].min() >= 0
    dlo = pos_host[ch].astype(np.int32)
    dhi = pos_host[new_parent[ch]].astype(np.int32)
    return dlo, dhi


def pad_actives_pow2(dlo, dhi, n: int, floor: int = 1 << 14):
    """Pad host (dlo, dhi) constraint arrays to a power-of-two length
    with the inert (n, n) sentinel so injected carries come from a small
    set of static shapes (one compile per bucket, not per delta)."""
    size = pow2_at_least(max(1, len(dlo)), floor=floor)
    out_lo = np.full(size, n, dtype=np.int32)
    out_hi = np.full(size, n, dtype=np.int32)
    out_lo[: len(dlo)] = dlo
    out_hi[: len(dhi)] = dhi
    return jnp.asarray(out_lo), jnp.asarray(out_hi)


class TailOverlap:
    """Worker-thread host-tail pipeline shared by the tpu backend and the
    tuning tool: submit compacted tails to :func:`host_tail_delta`, drain
    finished resolutions, and hand them back as padded injection carries.

    Use as a context manager so the single worker thread (and any
    in-flight O(V) pull) is released even when the driving loop raises —
    a leaked non-daemon thread blocks interpreter exit until its pending
    job finishes, which on a wedged device link means a hang instead of
    a fast failure."""

    def __init__(self, n: int, pos_host):
        from concurrent.futures import ThreadPoolExecutor

        self.n = n
        self.pos_host = pos_host
        self._executor = ThreadPoolExecutor(max_workers=1)
        self._pending: list = []   # in-flight futures, FIFO
        self._deltas: list = []    # resolved (dlo, dhi) awaiting injection

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._executor.shutdown(wait=True)
        return False

    def submit(self, P, loP, hiP) -> None:
        """Queue a compacted live tail (device arrays are immutable, so
        the P snapshot is safe to pull from the worker thread)."""
        self._pending.append(self._executor.submit(
            host_tail_delta, P, loP, hiP, self.n, self.pos_host))

    def drain(self, block: bool) -> None:
        while self._pending and (block or self._pending[0].done()):
            d = self._pending.pop(0).result()
            if len(d[0]):
                self._deltas.append(d)

    def take_inject(self):
        """All drained deltas as one padded (loP, hiP) carry, or None."""
        if not self._deltas:
            return None
        dlo = np.concatenate([d[0] for d in self._deltas])
        dhi = np.concatenate([d[1] for d in self._deltas])
        self._deltas.clear()
        return pad_actives_pow2(dlo, dhi, self.n)


def _fold_adaptive_pos_impl(*args, **kwargs):
    """:func:`_fold_adaptive_pos_impl_body` under the SHEEP_SANITIZE
    stray-sync guard: the adaptive driver's only designed host reads
    are the per-segment packed sv pull and the host-tail handoff —
    any other implicit device->host conversion in the loop raises."""
    with sanitize.guard("adaptive-fold"):
        return _fold_adaptive_pos_impl_body(*args, **kwargs)


def _fold_adaptive_pos_impl_body(
    P: jax.Array,
    loP: jax.Array,
    hiP: jax.Array,
    n: int,
    lift_levels: int,
    segment_rounds: int,
    descent: str,
    max_rounds: int,
    small_size: int,
    small_jumps: int,
    host_tail: bool,
    host_tail_threshold: int,
    warm_schedule: tuple,
    pos_host,
    stats,
    carry_out: bool,
    stale_tables: bool = True,
    stale_reuse: int = 1,
):
    """Shared adaptive-fixpoint loop; returns (P, total, carry) where
    ``carry`` is None (converged / host-finished) or a compacted
    (carry_loP, carry_hiP) of the still-live constraints (carry_out mode,
    see :func:`fold_edges_adaptive_pos_carry`).

    ``stale_reuse`` = full segments per lifting-stack rebuild (exact
    descent with stale_tables only). 1 = the landed per-segment
    hoisting; K > 1 reuses one stack across K segments
    (:func:`fold_segment_pos_stale`), cutting the (L-1) x V squaring
    gathers — the dominant V-term — by a further factor K at the price
    of weaker (never unsound) jumps between rebuilds."""
    from sheep_tpu.core import native

    # the CLI validates R:L >= 1 at parse time; validate the Python API
    # too — _resolve silently promotes levels <= 0 to FULL depth, the
    # opposite of a cheap warm round, so a malformed entry must fail
    # loudly here rather than quietly invert the schedule's intent
    for entry in warm_schedule:
        wr, wl = entry
        if wr < 1 or wl < 1:
            raise ValueError(
                f"warm_schedule entries must be (rounds >= 1, "
                f"lift_levels >= 1); got {tuple(entry)!r}")

    use_host_tail = host_tail and native.available() and pos_host is not None
    if stats is None:
        stats = {}
    _seed_ms_counters(stats)
    total = 0
    size = int(loP.shape[0])
    if host_tail_threshold <= 0:
        # auto: hand off once <= size/8 constraints remain (min 2^16) —
        # the cpu-jax sweet spot; on a real chip device rounds are far
        # cheaper relative to the host pass, so callers may lower it
        host_tail_threshold = max(1 << 16, size // 8)
    warm = list(warm_schedule)
    lift_stack = None
    segs_on_stack = 0

    def t_add(key: str, dt: float) -> None:
        # wall-clock attribution per segment KIND. Dispatches are async,
        # but each loop iteration ends in exactly ONE device pull (the
        # sv sync below), so iteration wall == that segment's true cost
        # — this is what decomposed the round-5 bad-link capture's
        # 227.8 s build (68 s device floor vs per-segment sync/transfer
        # tax; BASELINE.md round-5 capture section). Accumulate
        # UNROUNDED: consumers round at read time — a per-add 3-decimal
        # quantum over hundreds of segments can push sum(t_*) past the
        # measured wall on fast machines
        stats[key] = stats.get(key, 0.0) + dt

    prev_ready = None  # when the previous segment's sv pull completed
    while True:
        t0 = time.perf_counter()
        if prev_ready is not None:
            # host decision window between a stats pull and the next
            # fixpoint dispatch — an upper bound on device idle (the
            # rare dedup compactions dispatch device work inside it).
            # This driver is synchronous by design (its host decisions
            # need the stats); the in-flight batched pipeline is what
            # removes the window
            _t_ms(stats, "device_gap_ms", t0 - prev_ready)
        if warm and size > small_size:
            wrounds, wlevels = warm.pop(0)
            seg = min(wrounds, max_rounds - total)
            loP, hiP, P, sv = fold_segment_pos(
                P, loP, hiP, n, lift_levels=wlevels,
                segment_rounds=seg, descent="stream")
            stats["warm_segments"] = stats.get("warm_segments", 0) + 1
            t_key = "t_warm_s"
        elif size > small_size:
            seg = min(segment_rounds, max_rounds - total)
            rl, rd = _resolve(n, lift_levels, descent)
            if stale_tables and rd == "exact" and seg > 1:
                # exact descent with per-SEGMENT (stale) tables: saves
                # (seg-1)/seg of the L x V squaring gathers — the
                # round's dominant V-term (same unique fixpoint; see
                # fold_segment_pos_hoisted)
                if stale_reuse > 1:
                    if lift_stack is None or segs_on_stack >= stale_reuse:
                        # release the old stack BEFORE building the new
                        # one: both alive at once would transiently
                        # double the (EXACT_TABLE_BYTES-scale) footprint
                        lift_stack = None
                        lift_stack = build_lift_tables(P, n, rl)
                        segs_on_stack = 0
                        stats["stack_rebuilds"] = \
                            stats.get("stack_rebuilds", 0) + 1
                    loP, hiP, P, sv = fold_segment_pos_stale(
                        P, loP, hiP, lift_stack, n, segment_rounds=seg)
                    segs_on_stack += 1
                else:
                    loP, hiP, P, sv = fold_segment_pos_hoisted(
                        P, loP, hiP, n, lift_levels=rl, segment_rounds=seg)
            else:
                loP, hiP, P, sv = fold_segment_pos(
                    P, loP, hiP, n, lift_levels=lift_levels,
                    segment_rounds=seg, descent=descent)
            stats["full_segments"] = stats.get("full_segments", 0) + 1
            t_key = "t_full_s"
        else:
            seg = min(max(segment_rounds, 64), max_rounds - total)
            loP, hiP, P, sv = fold_segment_small_pos(
                P, loP, hiP, n, jumps=small_jumps, segment_rounds=seg)
            stats["small_segments"] = stats.get("small_segments", 0) + 1
            t_key = "t_small_s"
        # ONE device pull per segment for all three control scalars
        # (each pull is a full round-trip on a tunneled device); the
        # duplicate collapse happens inside the dedup compactions, which
        # run rarely — a per-segment distinct count would cost a
        # full-buffer two-key sort every segment (measured: seconds at
        # C=2^24 on the v5e, swamping the rounds it saved)
        t_pull = time.perf_counter()
        with sanitize.sync_ok("adaptive-sv-pull"):
            changed, r, live = \
                (int(x) for x in np.asarray(sv))  # sheeplint: sync-ok
        prev_ready = time.perf_counter()
        _t_ms(stats, "host_blocked_ms", prev_ready - t_pull)
        # dispatch-count attribution: one host->device SYNC per segment
        # is this driver's cost shape (each sv pull is a full link
        # round-trip); the batched dispatch (fold_segments_batch) exists
        # to amortize exactly this counter
        stats["host_syncs"] = stats.get("host_syncs", 0) + 1
        t_add(t_key, time.perf_counter() - t0)
        total += r
        stats["device_rounds"] = stats.get("device_rounds", 0) + r
        # live == 0 is the fixpoint too (the table only changes through
        # a retiring slot): return immediately rather than paying an
        # empty host tail / an all-dead carry buffer / one extra
        # confirming segment
        if not changed or live == 0 or total >= max_rounds:
            return P, total, None
        if live <= host_tail_threshold:
            if carry_out:
                # hand the still-live tail to the NEXT chunk's fold
                # instead of the host: the displaced cascade keeps
                # climbing inside the next chunk's (efficient, wide)
                # rounds, and the per-chunk O(V) table round-trip +
                # sequential native pass disappear. Sound because the
                # fixpoint is a property of the inserted constraint
                # multiset, not of when each constraint resolves.
                stats["carried_tails"] = stats.get("carried_tails", 0) + 1
                stats["carried_live"] = stats.get("carried_live", 0) + live
                cap = min(pow2_at_least(live, floor=1 << 14), size)
                return P, total, compact_actives(loP, hiP, n, cap,
                                                 dedup=True)
            if use_host_tail:
                stats["host_tails"] = stats.get("host_tails", 0) + 1
                stats["host_tail_live"] = \
                    stats.get("host_tail_live", 0) + live
                # size the pull by the live count, not the threshold:
                # the tail ships two O(size) arrays over the host link
                pull = pow2_at_least(live, floor=1 << 14)
                t0 = time.perf_counter()
                out = _host_tail_finish_pos(P, loP, hiP, n,
                                            min(pull, size), pos_host)
                t_add("t_host_tail_s", time.perf_counter() - t0)
                return out, total, None
        if size > small_size and live <= size // 2:
            new_size = pow2_at_least(2 * live, floor=small_size)
            if new_size < size:
                loP, hiP = compact_actives(loP, hiP, n, new_size,
                                           dedup=True)
                size = new_size
                stats["compactions"] = stats.get("compactions", 0) + 1


def fold_edges_adaptive_pos(
    P: jax.Array,
    loP: jax.Array,
    hiP: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 2,
    descent: str = "auto",
    max_rounds: int = 1 << 20,
    small_size: int = 1 << 14,
    small_jumps: int = 16,
    host_tail: bool = True,
    host_tail_threshold: int = 0,
    warm_schedule: tuple = (),
    pos_host=None,
    stats=None,
    stale_tables: bool = True,
    stale_reuse: int = 1,
):
    """Host-driven fixpoint with active-set compaction and a host-finished
    tail — same unique forest as :func:`fold_edges`, far less work.
    Everything stays in position space; callers carry P across chunks and
    convert to the vertex-space minp encoding only at phase boundaries.

    Measured motivation (RMAT-18, cpu-jax): 106 of 122 rounds had < 4k
    live constraints out of a 4.2M buffer, so >85% of build time was
    climbing dead slots and rebuilding lifting tables for them; at
    RMAT-20 the tail cascade alone was 6.8k rounds. Schedule:

    - warm phase: ``warm_schedule`` = ((rounds, lift_levels), ...)
      segments run FIRST with few lifting levels — on the real chip a
      full-buffer round's cost is ~linear in lift_levels x buffer width,
      and the bulk of the buffer retires in the first rounds without
      needing long jumps, so cheap warm rounds + compaction shrink the
      buffer before any full-depth round pays for it
    - full mode: lifting-table segments on the current buffer
    - after each segment, if live count <= size/2, compact the buffer to
      max(small_size, 2*live) rounded up to a power of two (each size is
      one extra compiled program; sizes shrink geometrically, so at most
      ~log4(C) programs exist)
    - once live <= ``host_tail_threshold`` and the native core is
      available, finish on host (:func:`_host_tail_finish_pos`): the
      displacement cascade is sequential work the CPU does in O(chain),
      for one O(V) table round-trip per chunk
    - fallback (no native core): jump-mode rounds at ``small_size`` —
      O(C') gathers per round, independent of V
    """
    P, total, _ = _fold_adaptive_pos_impl(
        P, loP, hiP, n, lift_levels, segment_rounds, descent, max_rounds,
        small_size, small_jumps, host_tail, host_tail_threshold,
        warm_schedule, pos_host, stats, carry_out=False,
        stale_tables=stale_tables, stale_reuse=stale_reuse)
    return P, total


def fold_edges_adaptive_pos_carry(
    P: jax.Array,
    loP: jax.Array,
    hiP: jax.Array,
    n: int,
    **opts,
):
    """Carry-out variant of :func:`fold_edges_adaptive_pos` for
    intermediate stream chunks: instead of host-finishing the tail, the
    still-live constraints are compacted and RETURNED as
    ``(P, rounds, (carry_loP, carry_hiP))`` for the caller to prepend to
    the next chunk's actives (empty carry when converged). Eliminates the
    per-chunk O(V) device->host->device table round-trip and the
    serialized native tail pass; only the stream's FINAL fold (on the
    last carry, via the plain entry point) pays one host tail. The final
    forest is identical — the fixpoint is determined by the inserted
    constraint multiset, not by when each constraint resolves
    (tests/test_tpu_ops.py pins streaming-vs-batch equality with carry
    on)."""
    args = (opts.pop("lift_levels", 0), opts.pop("segment_rounds", 2),
            opts.pop("descent", "auto"), opts.pop("max_rounds", 1 << 20),
            opts.pop("small_size", 1 << 14), opts.pop("small_jumps", 16),
            opts.pop("host_tail", True), opts.pop("host_tail_threshold", 0),
            opts.pop("warm_schedule", ()), opts.pop("pos_host", None),
            opts.pop("stats", None))
    stale = opts.pop("stale_tables", True)
    reuse = opts.pop("stale_reuse", 1)
    if opts:  # reject typos BEFORE the (potentially minutes-long) fold
        raise TypeError(f"unknown options: {sorted(opts)}")
    P, total, carry = _fold_adaptive_pos_impl(P, loP, hiP, n, *args,
                                              carry_out=True,
                                              stale_tables=stale,
                                              stale_reuse=reuse)
    if carry is None:
        carry = (jnp.zeros(0, jnp.int32), jnp.zeros(0, jnp.int32))
    return P, total, carry


def fold_edges_adaptive(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 2,
    descent: str = "auto",
    max_rounds: int = 1 << 20,
    small_size: int = 1 << 14,
    small_jumps: int = 16,
    host_tail: bool = True,
    host_tail_threshold: int = 0,
    warm_schedule: tuple = (),
    pos_host=None,
    stats=None,
):
    """Vertex-space wrapper of :func:`fold_edges_adaptive_pos` (one
    conversion each way; same unique forest)."""
    from sheep_tpu.core import native

    if host_tail and pos_host is None and native.available():
        # only pulled when a host tail can actually run — this is an
        # O(V) d2h transfer (~1 s at V=4M through the tunnel)
        pos_host = np.asarray(pos[:n])  # sheeplint: sync-ok
    P, total = fold_edges_adaptive_pos(
        minp[order], pos[lo], pos[hi], n, lift_levels=lift_levels,
        segment_rounds=segment_rounds, descent=descent,
        max_rounds=max_rounds, small_size=small_size,
        small_jumps=small_jumps, host_tail=host_tail,
        host_tail_threshold=host_tail_threshold,
        warm_schedule=warm_schedule, pos_host=pos_host, stats=stats)
    return P[pos], total


def fold_edges_segmented(
    minp: jax.Array,
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 32,
    descent: str = "auto",
    max_rounds: int = 1 << 20,
    on_segment=None,
):
    """Host-driven fixpoint: loop :func:`fold_edges_segment` until no slot
    changes. Same result as :func:`fold_edges`; one short device execution
    per ``segment_rounds`` rounds. ``on_segment(total_rounds)`` is called
    after each segment (progress/diagnostics hook)."""
    total = 0
    with sanitize.guard("segmented-fold"):
        while True:
            # never run past max_rounds: the tail segment shrinks to
            # the remaining budget so the result matches
            # fold_edges(max_rounds=...) exactly (one extra compile at
            # most, for the tail size)
            seg = min(segment_rounds, max_rounds - total)
            lo, hi, minp, changed, r = fold_edges_segment(
                minp, lo, hi, pos, order, n, lift_levels=lift_levels,
                segment_rounds=seg, descent=descent)
            # the designed per-segment control pull of this driver
            with sanitize.sync_ok("segmented-pull"):
                total += int(r)  # sheeplint: sync-ok
                done = not bool(changed)  # sheeplint: sync-ok
            if on_segment is not None:
                on_segment(total)
            if done or total >= max_rounds:
                return minp, total


def elim_fixpoint(
    lo: jax.Array,
    hi: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    max_rounds: int = 1 << 20,
    descent: str = "auto",
):
    """Elimination forest of an oriented constraint set, from scratch —
    :func:`fold_edges` seeded with the empty table."""
    return fold_edges(jnp.full(n + 1, n, dtype=jnp.int32), lo, hi, pos,
                      order, n, lift_levels=lift_levels,
                      max_rounds=max_rounds, descent=descent)


def tree_edges_from_parent(parent_pos: jax.Array, order: jax.Array, n: int):
    """parent_pos (minp) int32[n+1] -> (lo, hi) arrays of the forest edges,
    inactive slots as (n, n). lo = vertex, hi = its parent."""
    v = jnp.arange(n + 1, dtype=jnp.int32)
    has = parent_pos < n
    lo = jnp.where(has, v, n)
    hi = jnp.where(has, order[parent_pos], n)
    return lo, hi


@partial(jax.jit, static_argnames=("n", "lift_levels"))
def build_chunk_step(
    parent_pos: jax.Array,
    chunk: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
):
    """One streaming step: fold a (C, 2) edge chunk into the carried forest.

    parent_pos is the minp encoding (int32[n+1], n = no parent). The
    carried forest stays in the table — only the chunk's C edges are
    active (plus in-place displacement reuse), so per-chunk transients
    are O(C) and per-round work is independent of V. Device memory is
    O(V) tables + O(C) actives plus a bounded lifting-table stack (at
    most ``EXACT_TABLE_BYTES``; past that the stream descent keeps it
    one table) — the edge stream never materializes.
    """
    clo, chi = orient_edges(chunk, pos, n)
    return fold_edges(parent_pos, clo, chi, pos, order, n,
                      lift_levels=lift_levels)


def build_chunk_step_segmented(
    parent_pos: jax.Array,
    chunk: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 32,
):
    """:func:`build_chunk_step` with host-bounded device executions
    (:func:`fold_edges_segmented`) — the single-device streaming path uses
    this so no one accelerator call runs unboundedly long."""
    clo, chi = orient_edges(chunk, pos, n)
    return fold_edges_segmented(parent_pos, clo, chi, pos, order, n,
                                lift_levels=lift_levels,
                                segment_rounds=segment_rounds)


def build_chunk_step_adaptive(
    parent_pos: jax.Array,
    chunk: jax.Array,
    pos: jax.Array,
    order: jax.Array,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 2,
    warm_schedule: tuple = (),
    pos_host=None,
    stats=None,
    **fold_opts,
):
    """:func:`build_chunk_step` via :func:`fold_edges_adaptive`
    (compaction + host-finished tail) — same unique forest, bounded
    device executions, and the sequential displacement cascade runs on
    host instead of one link per device round."""
    clo, chi = orient_edges(chunk, pos, n)
    return fold_edges_adaptive(parent_pos, clo, chi, pos, order, n,
                               lift_levels=lift_levels,
                               segment_rounds=segment_rounds,
                               warm_schedule=warm_schedule,
                               pos_host=pos_host, stats=stats, **fold_opts)


@partial(jax.jit, static_argnames=("n",))
def orient_edges_pos(edges: jax.Array, pos: jax.Array, n: int):
    """(C,2) int32 edges -> oriented elimination POSITIONS (loP, hiP)
    with loP < hiP; self-loops and out-of-range/padding endpoints become
    the inert sentinel (n, n). pos is injective over vertices with
    pos[n] = n, so equal positions <=> same vertex or both padding."""
    e = edges.astype(jnp.int32)
    u = jnp.clip(e[:, 0], 0, n)
    v = jnp.clip(e[:, 1], 0, n)
    pu, pv = pos[u], pos[v]
    lo = jnp.minimum(pu, pv)
    hi = jnp.maximum(pu, pv)
    bad = lo == hi
    lo = jnp.where(bad, n, lo)
    hi = jnp.where(bad, n, hi)
    return lo, hi


def build_chunk_step_adaptive_pos(
    P: jax.Array,
    chunk: jax.Array,
    pos: jax.Array,
    pos_host,
    n: int,
    lift_levels: int = 0,
    segment_rounds: int = 2,
    warm_schedule: tuple = (),
    stats=None,
    **fold_opts,
):
    """One streaming step on the POSITION-SPACE carried table P — the
    single-device production fold: the backend carries P across chunks
    and converts to/from the vertex-space minp encoding only at phase
    (and checkpoint) boundaries, so the steady-state loop runs zero
    vertex<->position conversions. Extra ``fold_opts`` (e.g.
    host_tail_threshold) forward to :func:`fold_edges_adaptive_pos`.

    ``carry`` = (loP, hiP) actives carried over from the previous
    chunk's fold (prepended to this chunk's oriented actives);
    ``carry_out=True`` selects the carry-returning variant — the step
    then returns (P, rounds, carry) instead of (P, rounds)."""
    carry = fold_opts.pop("carry", None)
    carry_out = fold_opts.pop("carry_out", False)
    loP, hiP = orient_edges_pos(chunk, pos, n)
    if carry is not None and int(carry[0].shape[0]):
        loP = jnp.concatenate([loP, carry[0]])
        hiP = jnp.concatenate([hiP, carry[1]])
    fold = fold_edges_adaptive_pos_carry if carry_out \
        else fold_edges_adaptive_pos
    return fold(P, loP, hiP, n, lift_levels=lift_levels,
                segment_rounds=segment_rounds,
                warm_schedule=warm_schedule,
                pos_host=pos_host, stats=stats,
                **fold_opts)


@partial(jax.jit, static_argnames=("n", "lift_levels"))
def merge_forests(
    a_pos: jax.Array, b_pos: jax.Array, pos: jax.Array, order: jax.Array,
    n: int, lift_levels: int = 0,
):
    """Associative merge of two forests in minp encoding (SURVEY.md §2 #6):
    fold B's tree edges into A's table — T(A ∪ B) = T(T(A) ∪ T(B)).

    This is the cross-shard/device reduction combiner; the butterfly in
    ``parallel/pipeline.py`` ships each forest as either the O(V) table
    or compacted boundary pairs."""
    blo, bhi = tree_edges_from_parent(b_pos, order, n)
    minp, _ = fold_edges(a_pos, blo, bhi, pos, order, n,
                         lift_levels=lift_levels)
    return minp


def minp_to_parent(minp, order, n):
    """minp encoding -> parent array (int64[n], -1 for roots) on host."""
    minp = np.asarray(minp[:n])
    order = np.asarray(order)
    parent = np.where(minp < n, order[np.minimum(minp, n)], NO_PARENT)
    return parent.astype(np.int64)


def parent_to_minp(parent, pos, n):
    """parent array (int[n], -1 roots) -> device minp encoding int32[n+1]."""
    parent = np.asarray(parent)
    pos = np.asarray(pos)
    minp = np.full(n + 1, n, dtype=np.int32)
    has = parent >= 0
    minp[:n][has] = pos[parent[has]]
    return jnp.asarray(minp)
