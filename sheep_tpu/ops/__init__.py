from sheep_tpu.ops import degrees, elim, order, score, split  # noqa: F401
