"""Tree split dispatch (SURVEY.md §2 #7).

The split runs over O(V) tree state, not O(E) edges — it is two linear
passes and never the bottleneck at small V, but at the big eval configs
(41M–1B vertices, BASELINE.md) an interpreted per-vertex loop would
dominate the whole run. The TPU backends therefore route through the
native C++ split (core/csrc sheep_tree_split) whenever the library is
built, exactly like the cpu backend; the numpy/heapq reference in
``core/pure.py`` is the fallback and the executable spec. Both
implementations are bit-identical (stable descending child sort +
identical heap tie-breaking — asserted by tests/test_split_native.py),
so cross-backend edge-cut equivalence is unaffected by the dispatch.
Inputs arrive as device arrays; only the O(V) parent/pos tables cross
to host.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sheep_tpu.core import native, pure
from sheep_tpu.types import ElimTree


def tree_split_host(
    parent: np.ndarray,
    pos: np.ndarray,
    k: int,
    weights: Optional[np.ndarray] = None,
    alpha: float = 1.0,
) -> np.ndarray:
    parent64 = np.asarray(parent, dtype=np.int64)
    pos64 = np.asarray(pos, dtype=np.int64)
    if native.available():
        assign = native.tree_split(parent64, pos64, k, weights=weights,
                                   alpha=alpha)
    else:
        tree = ElimTree(parent=parent64, pos=pos64, n=len(parent64))
        assign = pure.tree_split(tree, k, weights=weights, alpha=alpha)
    account_split(assign, k, weights, alpha)
    return assign


def account_split(assign, k: int, weights, alpha: float) -> None:
    """Balance/capacity accounting of the split's output on the trace
    (ISSUE 13 cut ledger): the bag capacity is ``alpha * total/k``
    (+1 unit of slack the flushed-bag envelope allows), and parts the
    split already filled to it are FROZEN for downstream repair — the
    counter names how much of the residual the balance budget owns.
    Only when tracing is on: the O(V) bincount is pure ledger.
    Public: the cpu/pure backends call their native/pure split
    directly and route only the accounting through here."""
    from sheep_tpu import obs

    if not obs.enabled():
        return
    from sheep_tpu.ops.score import part_loads_accounting

    total = float(len(assign)) if weights is None \
        else float(np.sum(weights))
    # the contract ceiling: max part load <= (1 + alpha) * total/k
    # (+max_w slack) — BETA * total/k under --balance. Parts at it
    # cannot legally grow, whatever the cut says.
    acct = part_loads_accounting(assign, k, weights=weights,
                                 cap=(1.0 + alpha) * total / max(k, 1))
    obs.event("split_balance", k=k, alpha=float(alpha), **acct)
    obs.gauge("split_parts_at_capacity", acct["parts_at_capacity"])
