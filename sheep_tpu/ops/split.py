"""Tree split dispatch (SURVEY.md §2 #7).

The split runs over O(V) tree state, not O(E) edges — it is two linear
passes and never the bottleneck, so the default implementation runs on
host via the shared reference semantics in ``core/pure.py`` (identical
code path keeps cross-backend edge-cut equivalence exact). Inputs arrive
as device arrays; only the O(V) parent/pos tables cross to host.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sheep_tpu.core import pure
from sheep_tpu.types import ElimTree


def tree_split_host(
    parent: np.ndarray,
    pos: np.ndarray,
    k: int,
    weights: Optional[np.ndarray] = None,
    alpha: float = 1.0,
) -> np.ndarray:
    tree = ElimTree(parent=np.asarray(parent, dtype=np.int64),
                    pos=np.asarray(pos, dtype=np.int64), n=len(parent))
    return pure.tree_split(tree, k, weights=weights, alpha=alpha)
