"""Tree split dispatch (SURVEY.md §2 #7).

The split runs over O(V) tree state, not O(E) edges — it is two linear
passes and never the bottleneck at small V, but at the big eval configs
(41M–1B vertices, BASELINE.md) an interpreted per-vertex loop would
dominate the whole run. The TPU backends therefore route through the
native C++ split (core/csrc sheep_tree_split) whenever the library is
built, exactly like the cpu backend; the numpy/heapq reference in
``core/pure.py`` is the fallback and the executable spec. Both
implementations are bit-identical (stable descending child sort +
identical heap tie-breaking — asserted by tests/test_split_native.py),
so cross-backend edge-cut equivalence is unaffected by the dispatch.
Inputs arrive as device arrays; only the O(V) parent/pos tables cross
to host.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sheep_tpu.core import native, pure
from sheep_tpu.types import ElimTree


def tree_split_host(
    parent: np.ndarray,
    pos: np.ndarray,
    k: int,
    weights: Optional[np.ndarray] = None,
    alpha: float = 1.0,
) -> np.ndarray:
    parent64 = np.asarray(parent, dtype=np.int64)
    pos64 = np.asarray(pos, dtype=np.int64)
    if native.available():
        return native.tree_split(parent64, pos64, k, weights=weights,
                                 alpha=alpha)
    tree = ElimTree(parent=parent64, pos=pos64, n=len(parent64))
    return pure.tree_split(tree, k, weights=weights, alpha=alpha)
