"""Pallas VMEM-staged gather probe (SURVEY.md §7 step 7; VERDICT r3
weak #3).

CLOSED 2026-08-01: answered on real hardware — Mosaic rejects or
crashes on every gather form larger than one (8, 128) register tile,
probed exhaustively on-chip (tools/pallas_smoke.py --variant 1|2|3;
BASELINE.md
round-5 capture section), so XLA's native gather stands as the
hot-loop primitive by measurement. This module stays as the recorded
artifact of that evaluation and for the interpreter-mode semantics pin
(tests/test_pallas_gather.py); do not reopen without a new Mosaic
toolchain.

The build fixpoint is bound by random int32 gathers from the position
table. XLA's arbitrary-index gather measured ~100-150 M elem/s on the
v5e — ~50x under the HBM roofline — which is precisely the "XLA leaves
throughput on the table" situation SURVEY.md reserves Pallas for. The
open question (BASELINE.md closed it by argument only, which VERDICT r3
rejected): can a kernel that stages the table in VMEM (the P table is
1-17 MB at RMAT-18..22 — VMEM-resident territory, ~16 MB/core) and
gathers from there beat the XLA path >= 2x?

This module is the measurable form of that question. The kernel keeps
the whole table as one VMEM block and lets Mosaic lower the
``jnp.take``; index traffic is blocked over the grid. Two honest
outcomes on real hardware (``tools/microbench_fixpoint.py``
``pallas_vmem_gather_C``):

- it lowers and is faster -> a Pallas round body becomes the first
  credible path to single-chip R >= 1 (BASELINE.md revised thesis);
- Mosaic rejects the arbitrary-index take (the VPU is an 8x128
  elementwise engine without a general cross-VMEM gather) or it is no
  faster -> the gather roofline stands, now with an artifact instead
  of an argument.

``interpret=True`` runs the same kernel in interpreter mode on any
platform — that is what the unit test pins the semantics with.
"""

from __future__ import annotations

import functools


@functools.lru_cache(maxsize=None)
def _build(table_len: int, n_idx: int, block: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    try:  # memory-space constraint is TPU-only; interpret mode runs anywhere
        from jax.experimental.pallas import tpu as pltpu

        vmem = pltpu.VMEM
    except Exception:  # pragma: no cover - non-TPU jaxlib
        vmem = None

    def kernel(table_ref, idx_ref, out_ref):
        # whole table resident in VMEM; Mosaic decides whether an
        # arbitrary-index take is expressible on the VPU
        out_ref[...] = jnp.take(table_ref[...], idx_ref[...], axis=0,
                                mode="clip")

    def spec(block_shape, index_map):
        if vmem is None or interpret:
            return pl.BlockSpec(block_shape, index_map)
        return pl.BlockSpec(block_shape, index_map, memory_space=vmem)

    grid = (n_idx // block,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            spec((table_len,), lambda i: (0,)),     # full table, every step
            spec((block,), lambda i: (i,)),
        ],
        out_specs=spec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n_idx,), jnp.int32),
        interpret=interpret,
    )


def vmem_gather(table, idx, block: int = 8192, interpret: bool = False):
    """``table[idx]`` (clip-mode) with the table staged as one VMEM
    block. ``len(idx)`` must be a multiple of ``block``; the table must
    fit VMEM next to two index blocks (caller sizes it — 2^21 int32
    entries = 8 MB is the probe's cap)."""
    if len(idx) % block:
        raise ValueError(f"len(idx)={len(idx)} not a multiple of "
                         f"block={block}")
    return _build(len(table), len(idx), block, interpret)(table, idx)
