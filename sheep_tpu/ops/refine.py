"""Optional partition refinement: capacity-constrained label propagation
on device.

An EXTENSION beyond the reference's capability surface (SURVEY.md §2 has
no refinement component — the reference stops at the tree split): after
any backend produces an assignment, a few refinement rounds move
vertices to the neighbor-majority part under a balance cap, typically
cutting the edge cut further. Off by default so every cross-backend
parity test and the reference-equivalent pipeline are untouched; enable
with ``--refine N`` / ``sheep_tpu.partition(..., refine=N)``.

TPU shape: each half-round is one streamed scatter-add pass over the
edges into a (V, k) neighbor-part histogram, one argmax, and one
lexsorted capacity ranking — all static shapes, no data-dependent
control flow on device. Parallel moves are interleaved by vertex parity
(two half-rounds) to damp oscillation, and each full round is scored; a
round that does not improve the cut is ROLLED BACK and refinement stops,
so the refined cut is never worse than the input (guaranteed, not
heuristic).

Memory: the histogram is the only big buffer — 4*V*k bytes (int32).
When that exceeds ``budget_bytes`` the pass switches to VERTEX-BLOCKED
histograms: vertices are processed in contiguous blocks of Vb rows
(4*Vb*k <= budget), each block re-streaming the edges once — B =
ceil(V/Vb) edge passes per half-round instead of one, trading streams
for memory exactly like the build phase trades them (driver eval
configs: LiveJournal k=8 = 128 MB, one pass; twitter-2010 k=64 =
10.5 GB -> 3 blocked passes at a 4 GB budget).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n", "k"))
def neighbor_hist_chunk(hist: jax.Array, chunk: jax.Array,
                        assign: jax.Array, n: int, k: int):
    """Accumulate one (C, 2) edge chunk into the (n+1, k) neighbor-part
    histogram (row n absorbs padding/self-loops). Also returns this
    chunk's (cut, total) under the SAME validity mask — the score is a
    free by-product of the lookups the histogram already does, which
    lets the refine loop drop its separate per-round scoring pass."""
    e = chunk.astype(jnp.int32)
    u, v = e[:, 0], e[:, 1]
    valid = (u >= 0) & (u < n) & (v >= 0) & (v < n) & (u != v)
    pu = assign[jnp.clip(u, 0, n)]
    pv = assign[jnp.clip(v, 0, n)]
    iu = jnp.where(valid, u, n)
    iv = jnp.where(valid, v, n)
    cut = jnp.sum(valid & (pu != pv), dtype=jnp.int32)
    total = jnp.sum(valid, dtype=jnp.int32)
    hist = hist.at[iu, pv].add(1, mode="drop")
    return hist.at[iv, pu].add(1, mode="drop"), cut, total


@partial(jax.jit, static_argnames=("n", "k", "vb"))
def neighbor_hist_block(hist: jax.Array, chunk: jax.Array,
                        assign: jax.Array, base, n: int, k: int, vb: int):
    """Blocked variant: accumulate only rows [base, base+vb) of the
    global histogram into a (vb+1, k) buffer (row vb absorbs everything
    outside the block)."""
    e = chunk.astype(jnp.int32)
    u, v = e[:, 0], e[:, 1]
    valid = (u >= 0) & (u < n) & (v >= 0) & (v < n) & (u != v)
    pu = assign[jnp.clip(u, 0, n)]
    pv = assign[jnp.clip(v, 0, n)]

    def upd(h, i, p):
        local = jnp.where(valid, i, n) - base
        idx = jnp.where((local >= 0) & (local < vb), local, vb)
        return h.at[idx, p].add(1, mode="drop")

    return upd(upd(hist, u, pv), v, pu)


@partial(jax.jit, static_argnames=())
def hist_stats(hist: jax.Array, cur_part: jax.Array):
    """(rows, k) histogram -> (best part, best count, current count).

    ``current count`` doubles as the free cut measurement: summed over
    the real vertex rows it is 2 x intra edges (each intra edge (u, v)
    lands once in hist[u, p] and once in hist[v, p] with p the shared
    part), and the histogram's total over those rows is 2 x valid edges
    — the hist pass and score_chunk share the exact same validity mask,
    so ``cut = (hist_total - cur_total) // 2`` equals a scoring pass."""
    best = jnp.argmax(hist, axis=1).astype(jnp.int32)
    bestv = jnp.max(hist, axis=1)
    cur = jnp.take_along_axis(hist, cur_part[:, None].astype(jnp.int32),
                              axis=1)[:, 0]
    return best, bestv, cur


@partial(jax.jit, static_argnames=("n", "k"))
def plan_moves(best: jax.Array, gain: jax.Array, assign: jax.Array,
               cap: jax.Array, parity, n: int, k: int):
    """One half-round of capacity-constrained moves.

    A vertex of the active parity wants to move to its neighbor-majority
    part (``best``) when the ``gain`` (majority count minus current-part
    count) is strictly positive. Movers are ranked per target part by
    descending gain (one lexsort); only the top ``cap - load`` movers per
    part are accepted, so no part ever grows past the cap (departures
    only free more room). Returns the updated assignment.
    """
    vid = jnp.arange(n + 1, dtype=jnp.int32)
    cur_part = assign[:n + 1]
    want = (gain > 0) & (vid < n) & ((vid % 2) == parity)

    loads = jnp.zeros(k, jnp.int32).at[cur_part[:n]].add(1, mode="drop")
    head = jnp.maximum(cap - loads, 0)

    part_key = jnp.where(want, best, k)  # k = "not moving", sorts last
    order = jnp.lexsort((-gain, part_key))
    pk_sorted = part_key[order]
    starts = jnp.searchsorted(pk_sorted, jnp.arange(k, dtype=pk_sorted.dtype))
    pk_c = jnp.clip(pk_sorted, 0, k - 1)
    rank = jnp.arange(n + 1, dtype=jnp.int32) - starts[pk_c].astype(jnp.int32)
    ok_sorted = (pk_sorted < k) & (rank < head[pk_c])
    allowed = jnp.zeros(n + 1, bool).at[order].set(ok_sorted)
    return jnp.where(allowed, best, cur_part).astype(jnp.int32)


@partial(jax.jit, static_argnames=("n", "k"))
def plan_moves_weighted(best: jax.Array, gain: jax.Array, assign: jax.Array,
                        w: jax.Array, cap, parity, n: int, k: int):
    """Weighted variant of :func:`plan_moves`: per-part headroom is in
    vertex WEIGHT, and the accepted movers of each part are the longest
    gain-descending prefix whose cumulative weight fits the headroom
    (one global cumsum minus the part-start offset). float32 accumulation
    — caps are balance heuristics, so ~1e-7 relative slack is fine."""
    vid = jnp.arange(n + 1, dtype=jnp.int32)
    cur_part = assign[:n + 1]
    want = (gain > 0) & (vid < n) & ((vid % 2) == parity)

    wf = w.astype(jnp.float32)
    loads = jnp.zeros(k, jnp.float32).at[cur_part[:n]].add(wf[:n],
                                                           mode="drop")
    head = jnp.maximum(cap - loads, 0.0)

    part_key = jnp.where(want, best, k)
    order = jnp.lexsort((-gain, part_key))
    pk_sorted = part_key[order]
    w_sorted = jnp.where(pk_sorted < k, wf[order], 0.0)
    csum = jnp.cumsum(w_sorted)
    starts = jnp.searchsorted(pk_sorted, jnp.arange(k, dtype=pk_sorted.dtype))
    pk_c = jnp.clip(pk_sorted, 0, k - 1)
    base = jnp.where(starts > 0, csum[jnp.maximum(starts - 1, 0)], 0.0)
    within = csum - base[pk_c]  # inclusive prefix weight within the part
    ok_sorted = (pk_sorted < k) & (within <= head[pk_c])
    allowed = jnp.zeros(n + 1, bool).at[order].set(ok_sorted)
    return jnp.where(allowed, best, cur_part).astype(jnp.int32)


def plan_moves_host(best: np.ndarray, gain: np.ndarray, assign: np.ndarray,
                    cap, parity: int, n: int, k: int,
                    w: np.ndarray = None) -> np.ndarray:
    """Numpy mirror of plan_moves/plan_moves_weighted, for graphs whose
    O(V) planning buffers exceed the device budget (hosts hold hundreds
    of GB). Stable lexsorts on both sides -> identical accepted sets."""
    vid = np.arange(n + 1, dtype=np.int64)
    cur = assign[:n + 1]
    want = (gain > 0) & (vid < n) & ((vid % 2) == parity)
    part_key = np.where(want, best, k)
    order = np.lexsort((-gain, part_key))
    pk = part_key[order]
    starts = np.searchsorted(pk, np.arange(k))
    pk_c = np.clip(pk, 0, k - 1)
    if w is None:
        loads = np.bincount(cur[:n], minlength=k)
        head = np.maximum(cap - loads, 0)
        rank = np.arange(n + 1) - starts[pk_c]
        ok = (pk < k) & (rank < head[pk_c])
    else:
        wf = w.astype(np.float32)
        loads = np.bincount(cur[:n], weights=wf[:n],
                            minlength=k).astype(np.float32)
        head = np.maximum(np.float32(cap) - loads, 0.0)
        w_sorted = np.where(pk < k, wf[order], 0.0).astype(np.float32)
        csum = np.cumsum(w_sorted, dtype=np.float32)
        base = np.where(starts > 0, csum[np.maximum(starts - 1, 0)],
                        np.float32(0.0))
        within = csum - base[pk_c]
        ok = (pk < k) & (within <= head[pk_c])
    allowed = np.zeros(n + 1, bool)
    allowed[order] = ok
    return np.where(allowed, best, cur).astype(np.int32)


def _move_accounting(gain, before, after, parity: int, n: int):
    """(wanted, applied) of one half-round, for the quality ledger
    (ISSUE 13): ``wanted`` counts positive-gain movers of the active
    parity — vertices whose neighbor majority says "move"; ``applied``
    counts labels that actually changed. applied <= wanted always (the
    planner only ever accepts wanting movers), so wanted - applied is
    exactly the CAPACITY-BLOCKED count: repair the balance cap refused.
    Two small designed pulls per half-round — noise next to the O(E)
    stream pass each half-round already paid."""
    g = np.asarray(gain)  # sheeplint: sync-ok (ledger pull)
    vid = np.arange(g.shape[0])
    wanted = int(((g > 0) & (vid < n) & ((vid % 2) == parity)).sum())
    applied = int(np.asarray(before != after).sum())  # sheeplint: sync-ok
    return wanted, applied


def move_rescore_host(src, dst, prev, new, in_changed) -> int:
    """Exact edge-cut delta of a batch of part moves, from the moved
    vertices' arcs alone — the incremental scorer's move accounting
    (ISSUE 17), same vocabulary as :func:`_move_accounting` but over
    a symmetrized adjacency gather instead of a full stream pass.

    ``(src, dst)`` are every surviving arc LEAVING the changed set
    (``in_changed[src]`` all true is not required — arcs are masked
    here); ``prev``/``new`` the before/after assignments;
    ``in_changed`` a bool[V] mask of vertices whose label moved. Edges
    with both endpoints changed appear as two arcs; their (symmetric)
    contribution is halved, which is exact in integers because that
    partial sum is even. Self-loop arcs contribute 0 on both sides of
    the difference, so they need no special casing."""
    s = np.asarray(src)
    d = np.asarray(dst)
    if not len(s):
        return 0
    keep = in_changed[s]
    s, d = s[keep], d[keep]
    diff = ((new[s] != new[d]).astype(np.int64)
            - (prev[s] != prev[d]).astype(np.int64))
    both = in_changed[d]
    twice = int(diff[both].sum())
    assert twice % 2 == 0  # symmetric arcs: the both-changed sum is even
    return int(diff[~both].sum()) + twice // 2


def spool_stream(stream, n: int, chunk_edges: int = 1 << 22,
                 spool_dir: str = None):
    """Materialize a regeneration-expensive stream to a temp binary file
    once, returning (file_backed_stream, temp_path). Generator/counter-
    hash streams re-pay generation on EVERY pass (~all of refine's cost
    at soak scale — BASELINE.md refine table); a multi-pass consumer
    spools once and reads at disk/page-cache speed instead. Returns
    (stream, None) unchanged on any spooling failure (e.g. ENOSPC) —
    spooling is an optimization, never a requirement."""
    import os
    import tempfile

    from sheep_tpu.io.edgestream import EdgeStream

    wide = n > 0xFFFFFFFF
    dt = np.uint64 if wide else np.uint32
    # never commit to a write the disk can't hold: a known edge bound
    # must fit in (half of) the spool dir's free space; an unknown bound
    # skips spooling (better to re-generate than to fill a tmpfs /tmp)
    import shutil
    import sys

    ub = getattr(stream, "num_edges_upper_bound", None)
    target = spool_dir or tempfile.gettempdir()
    need = None if ub is None else 2 * dt().itemsize * ub
    try:
        free = shutil.disk_usage(target).free
    except OSError:
        free = 0
    if need is None or need > free // 2:
        print(f"refine: not spooling ({'unknown edge bound' if need is None else f'{need >> 20} MiB needed, {free >> 20} MiB free'})",
              file=sys.stderr)
        return stream, None
    fd = None
    path = None
    try:
        fd, path = tempfile.mkstemp(
            suffix=".bin64" if wide else ".bin32",
            prefix="sheep_spool_", dir=spool_dir)
        with os.fdopen(fd, "wb", buffering=1 << 20) as f:
            fd = None
            for c in stream.chunks(chunk_edges):
                f.write(np.ascontiguousarray(
                    np.asarray(c, np.int64).astype(dt)).tobytes())
        return EdgeStream.open(path, n_vertices=n), path
    except BaseException as e:
        # NEVER leak the partial write — also on non-OSError failures
        # raised by the source stream itself mid-spool (review finding)
        if fd is not None:
            os.close(fd)
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass
        if not isinstance(e, OSError):
            raise  # a broken SOURCE is the caller's problem, not spool's
        print(f"refine: stream spool failed ({e}); streaming direct",
              file=sys.stderr)
        return stream, None


def refine_assignment(assign: np.ndarray, stream, n: int, k: int,
                      rounds: int = 3, alpha: float = 1.10,
                      chunk_edges: int = 1 << 22,
                      budget_bytes: int = 4 << 30,
                      plan_budget_bytes: int = 4 << 30,
                      min_block: int = 1 << 16,
                      weights: np.ndarray = None,
                      spool: bool = True, spool_dir: str = None):
    """Refine a host assignment in place-semantics; returns
    (new_assign, refine_stats).

    Each round: two parity half-rounds of histogram + capped moves, then
    a scoring pass; a non-improving round is rolled back and refinement
    stops. The balance cap is ``alpha * ceil(n / k)`` vertices per part
    (with ``weights``: ``alpha * total_weight / k`` per part) — parts
    already above it only shrink.

    Refinement makes 2*rounds + 1 stream passes in full-histogram mode
    (each round's first histogram pass doubles as the previous round's
    scoring pass — the score reductions are fused into the histogram
    kernel) and 1 + rounds*(2*blocks + 1) in vertex-blocked mode (a
    dedicated 1-pass score stays cheaper there than a blocks-wide
    histogram pass). When the input is a generator/counter-hash stream
    (``fmt == "generator"``) it is spooled to a temp binary file first
    (``spool=False`` opts out, and streams whose edge bound is unknown
    or exceeds half the spool dir's free space stream direct) — one
    generation pass instead of one per refine pass (VERDICT r4 item 6).
    """
    import os

    spool_path = None
    if spool and getattr(stream, "fmt", None) == "generator":
        stream, spool_path = spool_stream(stream, n, chunk_edges,
                                          spool_dir)
    try:
        out, stats = _refine_impl(assign, stream, n, k, rounds, alpha,
                                  chunk_edges, budget_bytes,
                                  plan_budget_bytes, min_block, weights)
        stats["refine_spooled"] = int(spool_path is not None)
        return out, stats
    finally:
        if spool_path:
            try:
                os.unlink(spool_path)
            except OSError:
                pass


def _refine_impl(assign, stream, n, k, rounds, alpha, chunk_edges,
                 budget_bytes, plan_budget_bytes, min_block, weights):
    from sheep_tpu.backends.tpu_backend import pad_chunk
    from sheep_tpu.ops import score as score_ops

    # the move-planning step (lexsort + companion arrays) materializes
    # ~10 full-length O(V) single-device buffers with no blocked variant;
    # past the device budget, plan on HOST instead (numpy mirror of the
    # same math — hosts hold hundreds of GB)
    plan_bytes = 10 * 4 * (n + 1)
    host_plan = plan_bytes > plan_budget_bytes

    hist_bytes = 4 * (n + 1) * k
    vb = 0  # 0 = single full-width histogram
    if hist_bytes > budget_bytes:
        vb = max(min_block, budget_bytes // (4 * k))
        if vb >= n + 1:
            vb = 0

    def score(a_try):
        """Exact edge cut of ``a_try`` in ONE stream pass (blocked mode
        scores with this instead of a blocks-wide histogram pass)."""
        cuts = []
        for c in stream.chunks(chunk_edges):
            cc, _ = score_ops.score_chunk(
                jnp.asarray(pad_chunk(c,  # sheeplint: h2d-ok, spill-ok (refine re-stream, not the dispatch chain)
                                      chunk_edges, n)), a_try, n)
            cuts.append(cc)
        return sum(int(c) for c in cuts)

    def gains(a_try):
        """(best, gain, cut) over all vertices — one histogram pass, or
        ceil(V/vb) blocked passes when the full table exceeds budget.
        In full mode ``cut`` is the exact edge cut of ``a_try``'s
        labels, a free by-product of the pass (fused score reductions,
        synced once after the loop so dispatch stays pipelined); blocked
        mode returns cut=None — its score-only points use score()."""
        if not vb:
            cuts = []
            hist = jnp.zeros((n + 1, k), jnp.int32)
            for c in stream.chunks(chunk_edges):
                hist, cc, _ = neighbor_hist_chunk(
                    hist, jnp.asarray(pad_chunk(c,  # sheeplint: h2d-ok, spill-ok (refine re-stream, not the dispatch chain)
                                               chunk_edges, n)),
                    a_try, n, k)
                cuts.append(cc)
            b, bv, cur = hist_stats(hist, a_try)
            return b, bv - cur, sum(int(c) for c in cuts)
        best_h = np.zeros(n + 1, np.int32)
        gain_h = np.zeros(n + 1, np.int32)
        for base in range(0, n + 1, vb):
            hist = jnp.zeros((vb + 1, k), jnp.int32)
            for c in stream.chunks(chunk_edges):
                hist = neighbor_hist_block(
                    hist, jnp.asarray(pad_chunk(c,  # sheeplint: h2d-ok, spill-ok (refine re-stream, not the dispatch chain)
                                               chunk_edges, n)),
                    a_try, jnp.int32(base), n, k, vb)
            rows = a_try[base:base + vb]
            pad = vb - rows.shape[0]
            if pad:
                rows = jnp.concatenate([rows, jnp.zeros(pad, rows.dtype)])
            b, bv, cur = hist_stats(hist[:vb], rows)
            span = min(vb, n + 1 - base)
            # designed per-block gain pull of the host-planned refine
            best_h[base:base + span] = \
                np.asarray(b)[:span]  # sheeplint: sync-ok
            gain_h[base:base + span] = \
                np.asarray(bv - cur)[:span]  # sheeplint: sync-ok
        return jnp.asarray(best_h), jnp.asarray(gain_h), None

    def plan(b, g, a_try, parity):
        if host_plan:
            w_host = None if weights is None \
                else np.concatenate([np.asarray(weights, np.float32),
                                     np.zeros(1, np.float32)])
            return jnp.asarray(plan_moves_host(
                np.asarray(b), np.asarray(g), np.asarray(a_try),
                float(cap) if weights is not None else int(cap),
                parity, n, k, w=w_host))
        if weights is not None:
            return plan_moves_weighted(b, g, a_try, w_dev, cap,
                                       parity, n, k)
        return plan_moves(b, g, a_try, cap, parity, n, k)

    a_dev = jnp.asarray(np.concatenate(
        [np.asarray(assign, np.int32), np.zeros(1, np.int32)]))
    if weights is not None:
        w_dev = jnp.asarray(np.concatenate(
            [np.asarray(weights, np.float32), np.zeros(1, np.float32)]))
        cap = jnp.float32(alpha * float(np.sum(weights)) / k)
    else:
        cap = jnp.int32(int(alpha * (-(-n // k))))

    # Full-histogram mode runs 2R+1 passes instead of the old 1+3R:
    # each round's FIRST histogram pass also scores the previous round's
    # result (same labels), so the separate scoring pass is gone and the
    # rollback decision just moves to the top of the next iteration.
    # Trajectory is unchanged: parity-0 moves are planned from the
    # identical histogram that scored the accepted labels. Blocked mode
    # keeps a dedicated 1-pass score (a histogram "pass" there costs
    # ``blocks`` stream passes, so fusing would REGRESS pass counts —
    # review finding) for the same 1 + R*(2*blocks + 1) as before.
    from sheep_tpu import obs

    stats = {"refine_rounds_run": 0,
             "refine_hist_blocks": -(-(n + 1) // vb) if vb else 1,
             "refine_host_plan": int(host_plan),
             "refine_moves_wanted": 0, "refine_moves_applied": 0,
             "refine_moves_capacity_blocked": 0}
    best = a_try = a_dev
    best_cut = None
    pending = None  # move accounting of the round awaiting its score
    sp = obs.begin("refine", k=k, rounds_cap=rounds)
    try:
        for it in range(rounds + 1):
            if vb:
                b = g = None
                cut_now = score(a_try)
            else:
                b, g, cut_now = gains(a_try)
            if best_cut is None:
                best_cut = cut_now
                stats["refine_cut_before"] = cut_now
                # annotate-then-end: the starting cut is known rounds
                # before the span closes; put it on the interval now
                sp.annotate(cut_before=cut_now)
            else:
                accepted = cut_now < best_cut
                if pending is not None:
                    # the per-round ledger row (ISSUE 13): what the
                    # round wanted to move, what the capacity cap let
                    # through, and what the move bought — a rejected
                    # round reports its (non-positive) gain too, which
                    # is how "refine stopped because moves stopped
                    # paying" reads on the trace. The AGGREGATES only
                    # bank accepted rounds: a rejected round's moves
                    # are rolled back, so counting them would overstate
                    # the repair present in the shipped assignment.
                    obs.event("refine_round", cut=cut_now,
                              gain=best_cut - cut_now,
                              accepted=accepted, **pending)
                    if accepted:
                        stats["refine_moves_wanted"] += \
                            pending["moves_wanted"]
                        stats["refine_moves_applied"] += \
                            pending["moves_applied"]
                        stats["refine_moves_capacity_blocked"] += \
                            pending["moves_capacity_blocked"]
                        obs.inc("refine_moves_wanted",
                                pending["moves_wanted"])
                        obs.inc("refine_moves_applied",
                                pending["moves_applied"])
                        obs.inc("refine_moves_capacity_blocked",
                                pending["moves_capacity_blocked"])
                    pending = None
                if accepted:
                    best_cut, best = cut_now, a_try
                    stats["refine_rounds_run"] += 1
                else:
                    break  # roll back; refined result never regresses
            if it == rounds:
                break
            if vb:
                b, g, _ = gains(a_try)
            prev = a_try
            a_try = plan(b, g, a_try, 0)
            w0, m0 = _move_accounting(g, prev, a_try, 0, n)
            b, g, _ = gains(a_try)
            prev = a_try
            a_try = plan(b, g, a_try, 1)
            w1, m1 = _move_accounting(g, prev, a_try, 1, n)
            wanted, applied = w0 + w1, m0 + m1
            pending = {"round": it, "moves_wanted": wanted,
                       "moves_applied": applied,
                       "moves_capacity_blocked":
                           max(0, wanted - applied)}
    finally:
        sp.end(rounds_run=stats["refine_rounds_run"],
               cut_after=best_cut,
               moves_capacity_blocked=stats[
                   "refine_moves_capacity_blocked"])
    stats["refine_cut_after"] = best_cut
    return np.asarray(best[:n]), stats  # sheeplint: sync-ok
