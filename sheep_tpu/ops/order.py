"""Global elimination order on device (SURVEY.md §2 #3).

Vertices sorted by (degree asc, id asc). The id tie-break makes the order a
pure function of the global degree table, so every device/host derives the
identical order — the precondition for partial-tree mergeability.

A single *stable* int32 sort suffices: stable argsort over degrees breaks
ties by original index, i.e. by id — no 64-bit composite key needed, which
keeps the op fast on TPU (int64 is emulated there).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n",))
def elimination_order(deg: jax.Array, n: int):
    """deg: int[>=n] -> (pos int32[n+1], order int32[n+1]).

    pos[v] = elimination rank of v; order[p] = vertex at rank p. Both carry
    a sentinel slot at index n (pos[n] = n, order[n] = n) used by the
    elimination fixpoint as the "no vertex / +inf position" encoding.
    """
    order = jnp.argsort(deg[:n], stable=True).astype(jnp.int32)
    pos = jnp.zeros(n, dtype=jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    sentinel = jnp.array([n], dtype=jnp.int32)
    return jnp.concatenate([pos, sentinel]), jnp.concatenate([order, sentinel])
