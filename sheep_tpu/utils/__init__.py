from sheep_tpu.utils.checkpoint import Checkpointer, CheckpointState  # noqa: F401
from sheep_tpu.utils.fault import maybe_fail  # noqa: F401
