"""Fault classification + bounded retry policy (ISSUE 9 tentpole).

PR 8 made every path *resumable after* a process death; this module is
what keeps the process alive *through* a fault. Every recoverable error
the drivers see is classified into one of four fault classes, and a
:class:`RetryPolicy` decides — per class, with bounded attempts and
exponential backoff + jitter — whether the driver may retry:

    TRANSIENT    flaky I/O, link blips, UNAVAILABLE/DEADLINE_EXCEEDED
                 RPC-layer errors: retry in place, nothing to change.
    RESOURCE     RESOURCE_EXHAUSTED / OOM-class allocation failures:
                 retry only after the caller degrades its memory
                 footprint (the dispatch drivers halve dispatch_batch /
                 inflight via utils/membudget.degraded_dispatch and
                 drop the chunk cache before re-entering).
    DEVICE_LOSS  the accelerator (or its worker) went away: the caller
                 snapshots, reinitializes what it can in-process
                 (:func:`reinit_devices`), and resumes from the last
                 confirmed state.
    FATAL        everything else — bugs, bad input, the legacy
                 SHEEP_FAULT_INJECT kill injections. Never retried.

Classification is string-pattern based on top of exception types because
that is what the JAX/PJRT stack gives us: device errors surface as
``jaxlib.xla_extension.XlaRuntimeError`` whose *message* carries the
gRPC-style status (``RESOURCE_EXHAUSTED: ...``). Injected faults
(utils/fault.py) carry an explicit ``fault_class`` attribute so chaos
runs exercise exactly the production paths.

Knobs (environment, read once per policy construction):

    SHEEP_RETRY_MAX      attempts per fault class (default 3; 0 disables
                         in-process retry entirely — faults propagate,
                         the PR-8 kill+resume contract still applies)
    SHEEP_RETRY_BASE_S   first backoff delay in seconds (default 0.05)
"""

from __future__ import annotations

import os
import random
import time
from typing import Optional

TRANSIENT = "transient"
RESOURCE = "resource"
DEVICE_LOSS = "device_loss"
FATAL = "fatal"

# matched case-insensitively against "TypeName: message"
_RESOURCE_PATTERNS = (
    "resource_exhausted",
    "out of memory",
    "allocation failure",
    "failed to allocate",
    "oom",
)
_DEVICE_LOSS_PATTERNS = (
    "device_lost",
    "device lost",
    "device or resource busy",
    "failed_precondition: device",
    "tpu worker",
    "device is in an invalid state",
    "internal: failed to connect",
)
_TRANSIENT_PATTERNS = (
    "unavailable",
    "deadline_exceeded",
    "connection reset",
    "connection refused",
    "temporarily unavailable",
    "broken pipe",
    "try again",
)


def classify(exc: BaseException) -> str:
    """Fault class of an exception (see module docstring).

    Precedence: an explicit ``fault_class`` attribute (injected faults)
    wins; then exception types with unambiguous meaning; then message
    patterns, RESOURCE/DEVICE_LOSS before TRANSIENT so a message like
    "RESOURCE_EXHAUSTED while connection was open" degrades memory
    instead of spinning in-place retries.
    """
    cls = getattr(exc, "fault_class", None)
    if cls in (TRANSIENT, RESOURCE, DEVICE_LOSS, FATAL):
        return cls
    if isinstance(exc, MemoryError):
        return RESOURCE
    text = f"{type(exc).__name__}: {exc}".lower()
    for pat in _RESOURCE_PATTERNS:
        if pat in text:
            return RESOURCE
    for pat in _DEVICE_LOSS_PATTERNS:
        if pat in text:
            return DEVICE_LOSS
    if isinstance(exc, (OSError, IOError, TimeoutError)):
        # I/O errors without a more specific verdict above are worth one
        # bounded retry round (torn NFS reads, EINTR, transient EIO)
        return TRANSIENT
    for pat in _TRANSIENT_PATTERNS:
        if pat in text:
            return TRANSIENT
    return FATAL


class RetryPolicy:
    """Bounded per-fault-class retry budget with exponential backoff.

    One instance covers one logical operation (a build phase, a chunk
    stream): attempts are counted PER CLASS, so a run that survives two
    OOM degrades can still survive a later transient read blip. The
    jitter is seeded (``seed``) so chaos-soak replays are deterministic;
    production constructions leave it None (entropy-seeded).
    """

    def __init__(self, max_retries: Optional[int] = None,
                 base_delay_s: Optional[float] = None,
                 max_delay_s: float = 5.0, jitter: float = 0.5,
                 seed: Optional[int] = None):
        if max_retries is None:
            max_retries = int(os.environ.get("SHEEP_RETRY_MAX", "3"))
        if base_delay_s is None:
            base_delay_s = float(os.environ.get("SHEEP_RETRY_BASE_S",
                                                "0.05"))
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.max_retries = int(max_retries)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self.attempts = {TRANSIENT: 0, RESOURCE: 0, DEVICE_LOSS: 0}

    def admit(self, fault_class: str) -> bool:
        """True iff the policy has retry budget left for this class."""
        if fault_class not in self.attempts:
            return False  # FATAL (or unknown): never retried
        return self.attempts[fault_class] < self.max_retries

    def delay_s(self, attempt: int) -> float:
        """Backoff for the given 0-based attempt: base * 2^attempt,
        capped, with +/- ``jitter`` fraction randomized so a fleet of
        retrying workers doesn't stampede the same resource in sync."""
        d = min(self.base_delay_s * (2 ** max(0, attempt)),
                self.max_delay_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def record(self, fault_class: str, exc: BaseException,
               where: str = "") -> float:
        """Account one admitted fault: bump the class counter, emit the
        ``retry`` trace event (no-op untraced) and a stderr note, and
        return the backoff delay the caller should sleep. Call only
        after :meth:`admit` said yes."""
        import sys

        attempt = self.attempts[fault_class]
        self.attempts[fault_class] = attempt + 1
        d = self.delay_s(attempt)
        from sheep_tpu import obs

        obs.event("retry", fault_class=fault_class, where=where,
                  attempt=attempt + 1, max_retries=self.max_retries,
                  delay_s=round(d, 3),
                  error=f"{type(exc).__name__}: {str(exc)[:200]}")
        print(f"sheep retry: {fault_class} fault in {where or 'run'} "
              f"(attempt {attempt + 1}/{self.max_retries}, "
              f"backoff {d:.2f}s): {type(exc).__name__}: "
              f"{str(exc)[:200]}", file=sys.stderr)
        return d

    def backoff(self, fault_class: str, exc: BaseException,
                where: str = "") -> None:
        """record + sleep in one call (the common retry-loop epilogue)."""
        time.sleep(self.record(fault_class, exc, where=where))

    def run(self, fn, where: str = "", on_retry=None):
        """Call ``fn()`` under this policy: admitted faults back off and
        re-call; ``on_retry(exc, fault_class, attempt)`` (if given) runs
        between the backoff and the re-call — the hook where callers
        degrade buffers / reinitialize devices. Exhausted budgets and
        FATAL faults re-raise the original exception."""
        while True:
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified below
                cls = classify(exc)
                if not self.admit(cls):
                    raise
                self.backoff(cls, exc, where=where)
                if on_retry is not None:
                    on_retry(exc, cls, self.attempts[cls])


def handle_build_fault(policy: RetryPolicy, exc: BaseException,
                       where: str, stats: dict,
                       on_resource=None, on_device_loss=None) -> str:
    """The ONE fault epilogue of the drivers' build retry loops
    (tpu_backend / sharded pipeline): classify, check the per-class
    budget (re-raising FATAL and exhausted classes), count the retry
    in ``stats["dispatch_retries"]`` (the bench-gated trail), run the
    class-specific recovery hook, then back off. Returns the fault
    class when the caller should retry; never returns otherwise.

    The hooks carry the genuinely driver-specific halves —
    ``on_resource`` (degrade knobs, drop caches) and ``on_device_loss``
    (persist the driver's snapshot shape) — so the protocol itself
    (ordering, counters, events, budgets) lives in exactly one place."""
    cls = classify(exc)
    if not policy.admit(cls):
        raise exc
    stats["dispatch_retries"] = stats.get("dispatch_retries", 0) + 1
    if cls == RESOURCE and on_resource is not None:
        on_resource()
    elif cls == DEVICE_LOSS and on_device_loss is not None:
        on_device_loss()
    policy.backoff(cls, exc, where=where)
    return cls


def degrade_dispatch(n: int, chunk_edges: int, batch: int, inflight: int,
                     donate: bool, stats: dict, resume_chunk: int,
                     h2d_ring=None, residency=None):
    """Shared RESOURCE recovery step: pick the membudget-modeled
    halving of (dispatch_batch, inflight) — plus the staged H2D ring
    depth when the caller runs one (``h2d_ring`` an int, ISSUE 12) —
    record the degraded-knob counters + the ``dispatch_degraded`` trace
    event. Returns the new pair (or triple, mirroring
    ``membudget.degraded_dispatch``), or None when nothing is left to
    shed (the caller then plain-retries and ultimately falls back to
    the kill+resume contract).

    With a :class:`~sheep_tpu.utils.residency.ResidencyManager`
    (``residency``, ISSUE 20) the ladder spills BEFORE it shrinks:
    resident chunks are reclaimable HBM (their bits live on disk), so
    the first RESOURCE fault drops them — and halves the residency
    budget so refill pressure shrinks too — returning the dispatch
    knobs *unchanged*. Only a fault with nothing left to spill reaches
    the halving rungs below."""
    from sheep_tpu import obs
    from sheep_tpu.utils import membudget

    spillable = residency.spillable_bytes() if residency is not None \
        else 0
    nxt = membudget.degraded_dispatch(n, chunk_edges, batch, inflight,
                                      donate, h2d_ring=h2d_ring,
                                      spillable_bytes=spillable)
    if nxt is not None and nxt[0] == "spill":
        freed = residency.pressure_spill()
        stats["spill_degrades"] = stats.get("spill_degrades", 0) + 1
        obs.event("dispatch_spilled", resume_chunk=int(resume_chunk),
                  freed_bytes=int(freed),
                  residency_budget=int(residency.budget))
        return nxt[1:]
    if nxt is not None:
        stats["degraded_dispatch_batch"] = nxt[0]
        stats["degraded_inflight"] = nxt[1]
        event = {"dispatch_batch": nxt[0], "inflight": nxt[1]}
        if len(nxt) > 2:
            stats["degraded_h2d_ring"] = nxt[2]
            event["h2d_ring"] = nxt[2]
        obs.event("dispatch_degraded", resume_chunk=int(resume_chunk),
                  **event)
    return nxt


def recover_device_loss(stats: dict, resume_chunk: int,
                        save_snapshot=None) -> bool:
    """Shared DEVICE_LOSS recovery step: persist the driver's snapshot
    FIRST (``save_snapshot()`` — even if in-process recovery fails, the
    PR-8 kill+resume contract holds from here), then best-effort
    reinit, with the counter + ``device_reinit`` event trail."""
    from sheep_tpu import obs

    if save_snapshot is not None:
        save_snapshot()
    alive = reinit_devices()
    stats["device_loss_recoveries"] = \
        stats.get("device_loss_recoveries", 0) + 1
    obs.event("device_reinit", alive=bool(alive),
              resume_chunk=int(resume_chunk))
    return alive


def reinit_devices() -> bool:
    """Best-effort in-process device reinitialization after a
    DEVICE_LOSS-class fault: drop every compiled executable and live
    traced constant (they reference the dead client's buffers) so the
    retry re-stages everything fresh against whatever backend
    ``jax.devices()`` resolves next. Returns True when a device answered
    a trivial computation afterwards.

    This cannot resurrect a truly detached PJRT client in-process — for
    that the PR-8 kill+resume contract (checkpoint was saved before this
    call) remains the backstop — but it recovers the recoverable cases
    (worker restart behind the same client, preempted-then-restored
    chips, and every injected device loss in the chaos harness).
    """
    import jax

    try:
        jax.clear_caches()
    except Exception:
        pass
    try:
        import numpy as np

        dev = jax.local_devices()[0]
        probe = jax.device_put(np.int32(1), dev)
        return int(probe) == 1  # sheeplint: sync-ok
    except Exception:
        return False
