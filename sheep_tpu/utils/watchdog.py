"""Peer-liveness / straggler watchdog (ISSUE 9 tentpole).

A multi-host run whose peer dies (or whose network partitions) does not
crash — it HANGS in its next collective, silently, forever, burning the
reservation and telling nobody. The obs heartbeat (PR 2) already shows a
human that progress stopped; this module closes the loop in-process: a
daemon thread watches a progress clock the driver loop touches per
batch, and when nothing has been touched for ``timeout_s`` it

1. emits a ``straggler_timeout`` trace event + a stderr diagnosis
   (phase, last progress label, stall age, process rank) — the
   *diagnosed timeout* that replaces the silent hang, and
2. interrupts the main thread (``KeyboardInterrupt``) so the driver
   unwinds through its normal exception path — the last cadence
   checkpoint (saved by the streaming loops) makes the kill
   resumable, and
3. (only if ``escalate`` is set) hard-exits with :data:`EXIT_CODE`
   after a second timeout window, for the case where the interpreter
   never gets to process the interrupt because the main thread is
   wedged inside a blocking collective in native code. Supervisors
   (tools/run_paused_aware.sh auto-resume loop, tools/chaos_soak.py)
   treat that exit code as "stalled: resume me".

Enabled in the sharded drivers via ``SHEEP_PEER_TIMEOUT_S=<seconds>``
(off by default — single-host runs have nothing to watch and legitimate
jit warm-up can be minutes on big programs; pick a timeout well above
your slowest expected batch).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

EXIT_CODE = 121  # distinct "stalled, resumable" exit for supervisors

ENV_TIMEOUT = "SHEEP_PEER_TIMEOUT_S"


def env_timeout_s() -> float:
    """The SHEEP_PEER_TIMEOUT_S value, 0.0 when unset/invalid (off)."""
    try:
        return max(0.0, float(os.environ.get(ENV_TIMEOUT, "0") or "0"))
    except ValueError:
        return 0.0


class StallWatchdog:
    """Progress watchdog: ``touch()`` per unit of progress; a monitor
    thread converts ``timeout_s`` of silence into a diagnosed
    interrupt (see module docstring). Use as a context manager so the
    monitor never outlives the loop it watches."""

    def __init__(self, timeout_s: float, label: str = "run",
                 process: int = 0, escalate: bool = False,
                 poll_s: Optional[float] = None):
        if timeout_s <= 0:
            raise ValueError("watchdog timeout must be > 0 seconds")
        self.timeout_s = float(timeout_s)
        self.label = label
        self.process = int(process)
        self.escalate = bool(escalate)
        self._poll_s = poll_s if poll_s is not None \
            else min(1.0, self.timeout_s / 4)
        self._last = time.monotonic()
        self._last_what = "start"
        self._stop = threading.Event()
        self._fired = False
        self.fired_at: Optional[float] = None  # stall age when fired
        self._thread: Optional[threading.Thread] = None

    # -- driver-side API ---------------------------------------------------
    def touch(self, what: str = "") -> None:
        """Mark progress (cheap: two attribute writes, no locking — the
        monitor only ever reads, and a torn read merely shifts one poll
        by one interval)."""
        self._last = time.monotonic()
        if what:
            self._last_what = what

    def start(self) -> "StallWatchdog":
        self._last = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"sheep-watchdog-{self.label}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self._poll_s + 1.0)
            self._thread = None

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- monitor -----------------------------------------------------------
    def _diagnose(self, age: float) -> None:
        import sys

        from sheep_tpu import obs

        msg = (f"watchdog: no progress in {self.label!r} for "
               f"{age:.1f}s (timeout {self.timeout_s:.1f}s, last: "
               f"{self._last_what}, process {self.process}) — "
               f"interrupting the run; resume from the last checkpoint")
        print(f"sheep {msg}", file=sys.stderr)
        obs.event("straggler_timeout", label=self.label,
                  process=self.process, stalled_s=round(age, 1),
                  timeout_s=self.timeout_s, last=self._last_what)

    def _run(self) -> None:
        import _thread

        while not self._stop.wait(self._poll_s):
            age = time.monotonic() - self._last
            if age < self.timeout_s:
                continue
            if not self._fired:
                self._fired = True
                self.fired_at = age
                try:
                    self._diagnose(age)
                except Exception:
                    pass  # a broken sink must not mute the interrupt
                _thread.interrupt_main()
                # give the main thread one full window to unwind
                self._last = time.monotonic()
            elif self.escalate:
                # the interrupt never landed (main thread wedged in a
                # native collective): hard-exit so the supervisor's
                # auto-resume loop takes over — flush what we can first
                import sys

                print(f"sheep watchdog: interrupt did not unwind "
                      f"{self.label!r} within {self.timeout_s:.1f}s; "
                      f"hard exit {EXIT_CODE}", file=sys.stderr)
                sys.stderr.flush()
                try:
                    from sheep_tpu import obs

                    tr = obs.get_tracer()
                    if tr is not None:
                        tr.close()
                except Exception:
                    pass
                os._exit(EXIT_CODE)


def maybe_watchdog(procs: int, label: str, process: int = 0):
    """A started :class:`StallWatchdog` per the env knob, or None.
    Multi-process runs escalate to the hard exit (a wedged collective
    cannot process interrupts); single-process runs stop at the
    interrupt, which always lands there eventually."""
    t = env_timeout_s()
    if t <= 0 or procs < 1:
        return None
    return StallWatchdog(t, label=label, process=process,
                         escalate=procs > 1).start()


class _NullWatchdog:
    """Inert stand-in when the env knob is off: the driver loops call
    touch() unconditionally without branching per batch."""

    __slots__ = ()

    def touch(self, what: str = "") -> None:
        pass

    def stop(self) -> None:
        pass


NULL_WATCHDOG = _NullWatchdog()


class watched:
    """``with watched(procs, label, process) as wd`` — a started
    watchdog (or the inert null object) that is ALWAYS stopped on
    scope exit, so a driver exception can never leave a live monitor
    thread interrupting whatever the interpreter runs next."""

    def __init__(self, procs: int, label: str, process: int = 0):
        self._args = (procs, label, process)
        self._wd = None

    def __enter__(self):
        self._wd = maybe_watchdog(*self._args) or NULL_WATCHDOG
        return self._wd

    def __exit__(self, *exc) -> bool:
        if self._wd is not None:
            self._wd.stop()
        return False
