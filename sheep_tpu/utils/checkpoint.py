"""Checkpoint / resume (SURVEY.md §5 "Checkpoint / resume").

Partial elimination forests are associative, mergeable state, so the
natural unit of recovery is the *chunk*: persist ``(phase, next global
chunk index, O(V) arrays)`` every N chunks, and on restart re-open the
EdgeStream at the saved chunk index (``EdgeStream.chunks(start_chunk=...)``)
and continue. Each save costs O(V) bytes — independent of E, so
checkpointing a trillion-edge run is as cheap as a million-edge one.

Crash safety: the arrays go to a uniquely-named ``.npz`` written via a
temp file + ``os.replace``; the manifest (also atomically replaced) names
that file, so a crash at any instant leaves either the old or the new
checkpoint fully intact, never a torn one. Multi-host runs write one
checkpoint per process (``process`` tag in the filename), mirroring how the
reference would restart individual MPI ranks.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Dict, Optional

import numpy as np

# v2: sharded build-phase payload changed ('forest_all' O(V*d) stack ->
# 'merged_partial' O(V) merged forest) and the terminal 'done' phase was
# dropped from PHASES. _read_manifest returns None on a version mismatch,
# so v1 checkpoints degrade to a clean fresh start instead of a KeyError
# mid-recovery.
# v3: the 'hier' phase joins PHASES (hierarchy level-boundary state:
# level-0 result + spill-file manifest + per-part queue position, see
# sheep_tpu/hierarchy.py) and recovery degrades gracefully — a corrupt/
# truncated .npz or torn manifest falls back to the newest intact step
# (the retained previous one) or a clean start, with a warning, instead
# of raising mid-recovery.
FORMAT_VERSION = 3

# phase progression of every backend's pipeline (SURVEY.md §3.1) plus
# the hierarchy driver's level-boundary phase; a successful run clears
# its checkpoint instead of writing a terminal phase
PHASES = ("degrees", "build", "score", "hier")


# process-wide count of degraded recoveries, surfaced by the backends
# as the `checkpoint_degraded` diagnostic so silent degradation shows
# up in the perf trajectory (bench contract info field, ISSUE 9)
_DEGRADED_EVENTS = 0


def degraded_events() -> int:
    """How many checkpoint recoveries degraded in this process so far."""
    return _DEGRADED_EVENTS


def _warn(msg: str) -> None:
    """Degradation warning: stderr + a trace event (no-op untraced), so
    a resumed production run records that recovery was lossy."""
    import sys

    global _DEGRADED_EVENTS
    _DEGRADED_EVENTS += 1
    print(f"checkpoint warning: {msg}", file=sys.stderr)
    from sheep_tpu import obs

    obs.event("checkpoint_degraded", message=msg)


def phase_index(phase: str) -> int:
    return PHASES.index(phase)


@dataclasses.dataclass
class CheckpointState:
    phase: str
    chunk_idx: int  # next global chunk index to process in `phase`
    arrays: Dict[str, np.ndarray]
    meta: Dict

    def matches(self, meta: Dict) -> bool:
        """A checkpoint only resumes a run with identical inputs and
        options. Exact dict equality: a missing key on either side (e.g. a
        sharded-pipeline checkpoint resumed by the single-device backend,
        whose state arrays are shaped differently) is a mismatch."""
        return self.meta == meta


class Checkpointer:
    """Per-process checkpoint writer/reader rooted at a directory.

    ``every`` is the save cadence in chunks (or batches for the sharded
    pipeline); backends call :meth:`due` inside their streaming loops and
    :meth:`save` when it fires.
    """

    def __init__(self, directory: str, every: int = 64, process: int = 0,
                 auto_clear: bool = True):
        if every < 1:
            raise ValueError("checkpoint cadence must be >= 1 chunk")
        self.dir = directory
        self.every = int(every)
        self.process = int(process)
        # auto_clear=False suppresses the run-completion clear() the
        # backends issue, for NESTED recovery domains: hierarchy's
        # level-0 sub-run must leave its last chunk checkpoint on disk
        # until the parent has banked the level-0 result in its own
        # level-boundary checkpoint (a crash in that window otherwise
        # loses the whole level). The owner clears with clear(force=True).
        self.auto_clear = bool(auto_clear)
        os.makedirs(directory, exist_ok=True)

    def child(self, name: str, auto_clear: bool = False) -> "Checkpointer":
        """A checkpointer rooted at a subdirectory — a nested recovery
        domain with the same cadence/process (hierarchy hands one to its
        level-0 flat partition). Defaults to auto_clear=False: the
        parent decides when the child's state is safe to drop."""
        return Checkpointer(os.path.join(self.dir, name), every=self.every,
                            process=self.process, auto_clear=auto_clear)

    # -- cadence -----------------------------------------------------------
    def due(self, chunks_done: int) -> bool:
        return chunks_done > 0 and chunks_done % self.every == 0

    def due_span(self, before: int, after: int) -> bool:
        """True when the (before, after] chunk window crosses a cadence
        boundary — the right test when progress advances in strides (the
        sharded pipeline consumes d chunks per batch, and d need not
        divide ``every``)."""
        return after // self.every > before // self.every

    # -- paths -------------------------------------------------------------
    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, f"sheep_ckpt_p{self.process}.json")

    def _data_name(self, phase: str, chunk_idx: int) -> str:
        return f"sheep_ckpt_p{self.process}_{phase}_{chunk_idx}.npz"

    # -- save / load -------------------------------------------------------
    def save(self, phase: str, chunk_idx: int,
             arrays: Dict[str, np.ndarray], meta: Optional[Dict] = None) -> None:
        """Atomically persist a checkpoint step.

        The manifest records the latest step AND the immediately previous
        one, and the sweep keeps both data files. Multi-host runs need the
        previous step: host-side save skew across processes is at most one
        step (saves sit between lockstep collectives), so a process whose
        latest save is one step ahead of the common minimum can always
        fall back to its previous save (see
        ``reconcile_multihost_resume``)."""
        assert phase in PHASES, phase
        name = self._data_name(phase, chunk_idx)
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".npz.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.dir, name))
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        prev = None
        old = self._read_manifest(quiet=True)
        if old is not None:
            prev = {"phase": old["phase"], "chunk_idx": old["chunk_idx"],
                    "data": old["data"]}
        manifest = {
            "version": FORMAT_VERSION,
            "phase": phase,
            "chunk_idx": int(chunk_idx),
            "data": name,
            "previous": prev,
            "meta": meta or {},
        }
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".json.tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._manifest_path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        keep = {name}
        if prev is not None:
            keep.add(prev["data"])
        self._sweep(keep=keep)

    def _read_manifest(self, quiet: bool = False) -> Optional[Dict]:
        """``quiet`` suppresses the degradation warnings for callers
        that are not recovering (save() peeks at the old manifest for
        the previous-step entry; a stale/foreign manifest there is not
        a lossy recovery and must not fire a false alarm)."""
        try:
            with open(self._manifest_path) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            return None
        except (json.JSONDecodeError, OSError, UnicodeDecodeError):
            # a torn/corrupt manifest cannot name ANY step — the atomic
            # replace makes this near-impossible, but recovery must
            # degrade, not traceback (ISSUE 8 satellite)
            if not quiet:
                _warn(f"manifest {self._manifest_path} is torn/unreadable; "
                      f"starting clean")
            return None
        if manifest.get("version") != FORMAT_VERSION:
            if not quiet:
                _warn(f"checkpoint format v{manifest.get('version')} != "
                      f"v{FORMAT_VERSION}; starting clean (checkpoints are "
                      f"not portable across versions)")
            return None
        return manifest

    def _load_entry(self, entry: Dict, meta: Dict) -> Optional[CheckpointState]:
        data_path = os.path.join(self.dir, entry["data"])
        try:
            with np.load(data_path) as z:
                arrays = {k: z[k] for k in z.files}
        except Exception as exc:
            # a truncated .npz fails as BadZipFile/EOFError/zlib.error/
            # ValueError depending on WHERE the bytes stop — any of them
            # means this step is gone, and the caller falls back
            _warn(f"checkpoint data {entry.get('data')} unreadable "
                  f"({type(exc).__name__}: {exc})")
            return None
        return CheckpointState(
            phase=entry["phase"],
            chunk_idx=int(entry["chunk_idx"]),
            arrays=arrays,
            meta=meta,
        )

    def load(self) -> Optional[CheckpointState]:
        """Newest intact checkpoint: the manifest's latest step, falling
        back to its retained previous step when the latest data file is
        corrupt/missing, then to a clean start — each fallback warned,
        never raised (a torn checkpoint must not kill the recovery that
        exists to survive exactly such crashes)."""
        manifest = self._read_manifest()
        if manifest is None:
            return None
        meta = manifest.get("meta", {})
        for entry in (manifest, manifest.get("previous")):
            if not entry:
                continue
            state = self._load_entry(entry, meta)
            if state is not None:
                return state
        _warn(f"no intact checkpoint under {self.dir} (process "
              f"{self.process}); resuming as a clean start")
        return None

    def load_at(self, phase: str, chunk_idx: int) -> Optional[CheckpointState]:
        """Load the step (phase, chunk_idx) if it is the latest or the
        retained previous step; None otherwise."""
        manifest = self._read_manifest()
        if manifest is None:
            return None
        meta = manifest.get("meta", {})
        for entry in (manifest, manifest.get("previous")):
            if entry and entry["phase"] == phase \
                    and int(entry["chunk_idx"]) == int(chunk_idx):
                return self._load_entry(entry, meta)
        return None

    def clear(self, force: bool = False) -> None:
        """Drop this process's checkpoint state. With auto_clear=False
        (a nested child domain) only ``force=True`` clears — the
        backends' run-completion clear() becomes a no-op and the parent
        domain decides when the state is safe to drop."""
        if not self.auto_clear and not force:
            return
        self._sweep(keep=set())
        try:
            os.remove(self._manifest_path)
        except FileNotFoundError:
            pass

    def _sweep(self, keep: set) -> None:
        """Remove this process's stale data files (all but `keep`)."""
        prefix = f"sheep_ckpt_p{self.process}_"
        for fname in os.listdir(self.dir):
            if fname.startswith(prefix) and fname.endswith(".npz") and fname not in keep:
                try:
                    os.remove(os.path.join(self.dir, fname))
                except FileNotFoundError:
                    pass


def stream_meta(stream, k: int, chunk_edges: int, weights: str,
                alpha: float, comm_volume: bool, **extra) -> Dict:
    """Run fingerprint stored in the manifest; resume refuses to continue
    from a checkpoint whose fingerprint differs, because *every* option that
    affects the result is part of it — a different graph/k/chunking would
    corrupt the partition, a different alpha/weights would mix two
    assignments into one set of score counters, and a different comm_volume
    flag would undercount the cv_keys accumulated before the checkpoint."""
    meta = {
        "path": getattr(stream, "path", None),
        "n_vertices": int(stream.num_vertices),
        "k": int(k),
        "chunk_edges": int(chunk_edges),
        "weights": str(weights),
        "alpha": float(alpha),
        "comm_volume": bool(comm_volume),
    }
    # content identity, not just the name: a regenerated file at the same
    # path (same V, same E) must not resume against old partial state
    if meta["path"] is not None:
        try:
            st = os.stat(meta["path"])
            meta["file_size"] = int(st.st_size)
            meta["file_mtime_ns"] = int(st.st_mtime_ns)
        except OSError:
            pass
    elif getattr(stream, "_edges", None) is not None:
        # in-memory stream: hash a bounded sample so two arrays with the
        # same (V, E) but different edges cannot cross-resume
        import hashlib

        e = stream._edges
        sample = np.ascontiguousarray(np.concatenate([e[:4096], e[-4096:]]))
        meta["content_sha1"] = hashlib.sha1(sample.tobytes()).hexdigest()
    elif getattr(stream, "content_fingerprint", None) is not None:
        # streams that know a cheap stable identity (e.g. RmatHashStream:
        # parameters + a small hashed prefix) provide it directly — the
        # factory fallback below would materialize a full default-size
        # chunk inside every timed partition() call
        meta["content_sha1"] = str(stream.content_fingerprint())
    elif getattr(stream, "_factory", None) is not None:
        # generator stream: hash the first block (factories replay
        # deterministically, so this is a stable content fingerprint)
        import hashlib

        first = next(iter(stream._factory()), None)
        if first is not None:
            sample = np.ascontiguousarray(
                np.asarray(first, dtype=np.int64)[:4096])
            meta["content_sha1"] = hashlib.sha1(sample.tobytes()).hexdigest()
    m = stream.num_edges_cheap
    if m is not None:
        meta["num_edges"] = int(m)
    meta.update(extra)
    return meta


def compact_cv_keys(cv_chunks) -> np.ndarray:
    """Merge accumulated cut-pair key arrays into one sorted unique array
    (the comm-volume accumulator; SURVEY.md §2 #8)."""
    if not cv_chunks:
        return np.zeros(0, np.int64)
    return np.unique(np.concatenate(cv_chunks))


def save_score_state(checkpointer: Checkpointer, chunk_idx: int, cut: int,
                     total: int, cv_chunks, extra_arrays: Dict, meta: Dict,
                     comm_volume: bool):
    """Shared score-phase checkpoint: compact the cv-key accumulator, save
    it with the counters, and return the compacted accumulator list the
    caller should carry forward (empty when comm_volume is off)."""
    keys = compact_cv_keys(cv_chunks)
    checkpointer.save(
        "score", chunk_idx,
        {**extra_arrays, "cut": np.int64(cut), "total": np.int64(total),
         "cv_keys": keys}, meta)
    return [keys] if comm_volume else []


# Sentinel returned by resume_state(raise_on_mismatch=False): the local
# checkpoint exists but does not fingerprint-match this run. Multi-host
# callers pass it to reconcile_multihost_resume so the failure is raised
# on EVERY process via the ok-allgather — raising eagerly on one process
# would leave the others blocked in their first collective until the
# distributed timeout (each host stats its own input copy, so a single
# re-synced host can mismatch alone).
MISMATCHED = object()


def resume_state(checkpointer: Optional[Checkpointer], meta: Dict,
                 resume: bool, raise_on_mismatch: bool = True):
    """Load-and-validate helper shared by the backends.

    Returns the CheckpointState, None (nothing to resume), or — only when
    ``raise_on_mismatch`` is False — the ``MISMATCHED`` sentinel.
    """
    if checkpointer is None or not resume:
        return None
    state = checkpointer.load()
    if state is None:
        return None
    if not state.matches(meta):
        if not raise_on_mismatch:
            return MISMATCHED
        raise ValueError(
            "checkpoint does not match this run "
            f"(saved {state.meta}, current {meta}); "
            "pass a fresh --checkpoint-dir or drop --resume. Note: "
            "upgrading sheep_tpu can change automatic chunk sizing "
            "(part of the fingerprint), in which case restart fresh — "
            "checkpoints are not portable across versions")
    # the trace records where a killed run restarted, so trace_report
    # can show the death/resume seam alongside the UNCLOSED spans of
    # the previous (killed) run in the same appended file
    from sheep_tpu import obs

    obs.event("resume", phase=state.phase, chunk_idx=int(state.chunk_idx),
              process=checkpointer.process)
    return state


def reconcile_multihost_resume(checkpointer: Checkpointer,
                               state,
                               meta: Dict) -> Optional[CheckpointState]:
    """Agree on one global resume step across processes.

    Per-process manifests can be skewed by exactly one save step (a crash
    between one process's save and another's); resuming from skewed steps
    would desynchronize the collective schedules and hang the run. All
    processes allgather their latest (phase, chunk) step and fall back to
    the common minimum — each process either already holds it, or holds it
    as its retained *previous* step. No common step -> fresh start.

    Failure is collective: whether every process can produce the common
    step is itself allgathered, so an unrecoverable skew — or a local
    fingerprint mismatch (``state is MISMATCHED``, from
    ``resume_state(raise_on_mismatch=False)``) — raises on ALL processes
    instead of leaving the healthy ones hanging in their first collective
    while one process exits.
    """
    from jax.experimental import multihost_utils

    mismatched = state is MISMATCHED
    own = ((phase_index(state.phase), state.chunk_idx)
           if state and not mismatched else (-1, -1))
    allsteps = np.asarray(multihost_utils.process_allgather(
        np.array(own, dtype=np.int64)))
    lex = sorted(map(tuple, allsteps.reshape(-1, 2).tolist()))
    lo_phase, lo_chunk = lex[0]
    fresh = lo_phase < 0  # someone has no checkpoint at all: start fresh
    candidate: Optional[CheckpointState] = None
    if not fresh:
        if (lo_phase, lo_chunk) == own:
            candidate = state
        else:
            candidate = checkpointer.load_at(PHASES[lo_phase], lo_chunk)
        if candidate is not None and not candidate.matches(meta):
            candidate = None
    ok = (fresh or candidate is not None) and not mismatched
    all_ok = np.asarray(multihost_utils.process_allgather(
        np.array([1 if ok else 0], dtype=np.int64)))
    if not all_ok.all():
        raise ValueError(
            f"cannot resume: common step {(lo_phase, lo_chunk)} is not "
            f"retained, does not match this run, or a local checkpoint "
            f"fingerprint-mismatched on some process "
            f"(this process has {own}, ok={ok}, mismatched={mismatched}); "
            "pass a fresh --checkpoint-dir or drop --resume")
    return None if fresh else candidate
