"""Chunk residency manager: device memory as a cache tier (ISSUE 20).

Every scale ceiling so far has been device memory: ``membudget`` could
only *shrink* dispatch until a build fit, and past batch=1 the served
scheduler rejected outright. This module turns the budget into a cache
policy instead of an admission ceiling — a byte-accounted residency
plane over tiers that already exist:

    disk   the stream itself (mmap CSR via io/csr.py, edge files, the
           PR-8 spill manifests): every chunk is reconstructible from
           its on-disk bytes, so *eviction is exactly the PR-8
           crash-recovery path run live* — dropping a device chunk
           loses nothing but the re-upload latency, which the PR-12
           staged H2D ring already hides.
    HBM    the resident entries held here (the chunk cache the backend
           and the served scheduler always had, now with eviction).

Residency policy (why two tiers inside the budget):

- **sticky prefix** — chunks are admitted greedily from the stream
  head, exactly the proven `_ChunkCache` prefix semantics: the three
  streaming passes (degrees/build/score) all read from chunk 0, so for
  cyclic access keeping the *lowest* indices resident is optimal (LRU
  would thrash: it evicts precisely the chunks the next pass needs
  first).
- **rotating tail window** — once the stream outgrows the budget, a
  slice of the budget is carved out of the prefix top and rotated over
  the chunks *since the last confirmed checkpoint*: an intra-attempt
  retry (OOM degrade, device loss) re-folds from the snapshot index,
  and the window serves those re-reads from HBM instead of the host.
  **Checkpoint boundaries are the eviction points** —
  :meth:`ResidencyManager.boundary` drops window entries behind the
  confirmed index, because once a checkpoint confirms chunk i the only
  path that re-reads [0, i) is a later *pass* (served by the prefix) —
  never a retry.

Exactness is by construction, not policy: chunks are immutable edge
data and ``pad_chunk`` is deterministic, so eviction/reload changes
*where* bytes live, never which bits the fixpoint folds — a build under
a deliberately tiny budget is bit-identical to the unconstrained
oracle (the PR-1/PR-3 order-independence invariant).

Counters (written into the caller's stats dict, flowing to
``PartitionResult.diagnostics`` -> the bench record -> bench_regress):

    spill_evictions       entries dropped from HBM
    spill_reload_bytes    bytes re-uploaded for previously evicted ids
    spill_resident_bytes  resident-set high-water mark
    residency_hits        chunk serves that skipped the host

The manager holds opaque refs and never imports jax: eviction drops
the *manager's* reference — a consumer still holding the array (an
in-flight batched execution) keeps the device buffer alive, which is
why eviction can never corrupt issued work. Leases exist for
*accounting honesty*: a leased chunk's bytes must not be modeled as
reclaimable, so eviction refuses it (:class:`LeasedChunkError`) and
the spill scans skip it.
"""

from __future__ import annotations

from typing import Optional

#: tier tags for resident entries
_PREFIX = 0
_WINDOW = 1


class LeasedChunkError(RuntimeError):
    """Eviction was asked to drop a chunk some consumer still leases."""


class _Entry:
    __slots__ = ("ref", "nbytes", "tier", "leases")

    def __init__(self, ref, nbytes: int, tier: int):
        self.ref = ref
        self.nbytes = int(nbytes)
        self.tier = tier
        self.leases = 0


def manager_from_env(stats: Optional[dict] = None,
                     window_fraction: float = 0.25):
    """:class:`ResidencyManager` from an explicit ``SHEEP_CACHE_BYTES``
    budget, or None when unset/non-positive — the sharded drivers'
    opt-in hook (the tpu backend additionally auto-sizes from detected
    HBM; the sharded collectives only engage residency under an
    explicit budget, where the operator owns the HBM split)."""
    import os

    try:
        budget = int(os.environ.get("SHEEP_CACHE_BYTES", "0") or "0")
    except ValueError:
        budget = 0
    if budget <= 0:
        return None
    return ResidencyManager(budget, stats=stats,
                            window_fraction=window_fraction)


class ResidencyManager:
    """Byte-accounted device residency for streamed chunks.

    ``budget_bytes`` caps the resident set; ``stats`` (optional dict —
    typically the driver's build_stats) receives the spill counters so
    they ride the existing diagnostics plumbing unchanged.
    ``window_fraction`` bounds the rotating tail window carved out once
    the stream overflows the budget (the carve only happens *on first
    overflow*, so a stream that fits keeps the whole budget as prefix —
    exactly the legacy `_ChunkCache` behavior, zero evictions)."""

    def __init__(self, budget_bytes: int, stats: Optional[dict] = None,
                 window_fraction: float = 0.25):
        self.budget = max(0, int(budget_bytes))
        self.stats = stats if stats is not None else {}
        self.window_fraction = float(window_fraction)
        self.entries: dict = {}          # idx -> _Entry
        self.used = 0
        self.complete = False
        self._overflowed = False         # stream outgrew the budget once
        self._window_budget = 0          # carved on first overflow
        self._window_used = 0
        self._window_order: list = []    # admission order (FIFO rotation)
        self._evicted: set = set()       # ids once resident, since dropped

    # -- counters ------------------------------------------------------
    def _count(self, key: str, delta) -> None:
        self.stats[key] = self.stats.get(key, 0) + delta

    def _high_water(self) -> None:
        if self.used > self.stats.get("spill_resident_bytes", 0):
            self.stats["spill_resident_bytes"] = self.used

    def spillable_bytes(self) -> int:
        """Bytes the spill scans could free right now (unleased)."""
        return sum(e.nbytes for e in self.entries.values()
                   if e.leases == 0)

    # -- serving -------------------------------------------------------
    def get(self, idx: int):
        """Resident ref for chunk ``idx`` or None (host/disk re-read)."""
        e = self.entries.get(idx)
        if e is None:
            return None
        self._count("residency_hits", 1)
        return e.ref

    def admit(self, idx: int, ref, nbytes: int) -> bool:
        """Offer an uploaded chunk for residence; returns True when
        retained. Re-uploads of previously evicted ids are counted as
        reloads whether or not they are re-retained (the reload cost —
        the host->device transfer — was paid either way)."""
        nbytes = int(nbytes)
        if idx in self._evicted:
            self._count("spill_reload_bytes", nbytes)
            self._count("spill_reloads", 1)
            self._evicted.discard(idx)
        if self.budget <= 0:
            return False
        old = self.entries.get(idx)
        if old is not None:
            old.ref = ref  # refresh (same bits; same accounted size)
            return True
        if not self._overflowed:
            if self.used + nbytes <= self.budget:
                self.entries[idx] = _Entry(ref, nbytes, _PREFIX)
                self.used += nbytes
                self._high_water()
                return True
            # first overflow: carve the rotating window out of the
            # prefix top — from here on the stream is out-of-core
            self._overflowed = True
            # at least one chunk wide so rotation can make progress,
            # clamped to the budget so the cap holds even when a single
            # chunk exceeds it (such a chunk is refused below)
            self._window_budget = min(self.budget, max(
                nbytes, int(self.budget * self.window_fraction)))
            self._shrink_prefix_to(self.budget - self._window_budget)
        # window admission: rotate out the oldest unleased window
        # entries until this chunk fits the carve-out
        if nbytes > self._window_budget:
            return False
        while self._window_used + nbytes > self._window_budget:
            if not self._rotate_window():
                return False  # everything left is leased
        self.entries[idx] = _Entry(ref, nbytes, _WINDOW)
        self._window_order.append(idx)
        self._window_used += nbytes
        self.used += nbytes
        self._high_water()
        return True

    def note_stream_end(self, total_chunks: int) -> None:
        """A head-anchored pass consumed the whole stream: when every
        chunk stayed resident, later passes serve entirely from HBM
        (the legacy cache's ``complete`` fast path)."""
        if not self._overflowed and not self._evicted \
                and len(self.entries) >= total_chunks:
            self.complete = True

    # -- leases --------------------------------------------------------
    def lease(self, idx: int) -> None:
        e = self.entries.get(idx)
        if e is not None:
            e.leases += 1

    def release(self, idx: int) -> None:
        e = self.entries.get(idx)
        if e is not None and e.leases > 0:
            e.leases -= 1

    # -- eviction ------------------------------------------------------
    def _drop(self, idx: int) -> int:
        e = self.entries.pop(idx)
        self.used -= e.nbytes
        if e.tier == _WINDOW:
            self._window_used -= e.nbytes
            try:
                self._window_order.remove(idx)
            except ValueError:
                pass
        self._evicted.add(idx)
        self._count("spill_evictions", 1)
        return e.nbytes

    def evict(self, idx: int) -> int:
        """Drop one resident chunk; refuses a leased one — its bytes
        are not reclaimable while a consumer holds it for issued work."""
        e = self.entries.get(idx)
        if e is None:
            return 0
        if e.leases > 0:
            raise LeasedChunkError(
                f"chunk {idx} has {e.leases} active lease(s); its bytes "
                "are pinned by in-flight work and cannot be evicted")
        return self._drop(idx)

    def _rotate_window(self) -> bool:
        for idx in list(self._window_order):
            if self.entries[idx].leases == 0:
                self._drop(idx)
                return True
        return False

    def _shrink_prefix_to(self, target_bytes: int) -> int:
        """Evict unleased prefix entries top-down (highest idx first —
        the lowest indices are the ones every later pass re-reads
        first) until the prefix fits ``target_bytes``."""
        freed = 0
        prefix_used = self.used - self._window_used
        for idx in sorted((i for i, e in self.entries.items()
                           if e.tier == _PREFIX), reverse=True):
            if prefix_used <= target_bytes:
                break
            if self.entries[idx].leases:
                continue
            nb = self._drop(idx)
            prefix_used -= nb
            freed += nb
        return freed

    def boundary(self, confirmed_idx: int) -> int:
        """Checkpoint boundary = eviction point: window entries behind
        the confirmed index can only ever be re-read by a later *pass*
        (the prefix's job), never by a retry — their recovery state is
        on disk now. Returns bytes freed."""
        freed = 0
        for idx in list(self._window_order):
            if idx < confirmed_idx and self.entries[idx].leases == 0:
                freed += self._drop(idx)
        if freed:
            self._count("residency_boundary_evictions", 1)
        return freed

    def spill(self, target_bytes: Optional[int] = None) -> int:
        """Free resident bytes under memory pressure: window first
        (oldest first — coldest for a head-anchored re-read), then the
        prefix top-down. ``None`` spills everything unleased."""
        freed = 0
        for idx in list(self._window_order):
            if target_bytes is not None and freed >= target_bytes:
                return freed
            if self.entries[idx].leases == 0:
                freed += self._drop(idx)
        remaining = None if target_bytes is None \
            else max(0, target_bytes - freed)
        if remaining is None or remaining > 0:
            freed += self._shrink_prefix_to(
                0 if remaining is None
                else max(0, (self.used - self._window_used) - remaining))
        return freed

    def pressure_spill(self) -> int:
        """The RESOURCE-fault spill step (spill-before-shrink, threaded
        via utils/retry.degrade_dispatch): drop everything unleased AND
        halve the budget, so the refill pressure shrinks with the
        device that just proved too small. Repeated faults walk the
        budget to 0 — the point where the degrade ladder falls through
        to halving dispatch knobs, exactly the old behavior."""
        freed = self.spill(None)
        self.budget //= 2
        self._overflowed = self.budget > 0 and self._overflowed
        self._window_budget = min(self._window_budget, self.budget)
        self.complete = False
        return freed
