"""Structured JSON-lines metrics (SURVEY.md §5 "Metrics / logging").

The reference printed scores to stdout; the rebuild's observability
contract is machine-readable: one JSON object per line, appended to a
file (or any writable handle), covering per-phase throughput, partition
quality, per-part loads, and device-memory high-water marks where the
platform exposes them.

Usage:
    mw = MetricsWriter(path)
    mw.emit("phase", phase="build", seconds=2.3, edges_per_sec=1.2e8)
    mw.close()
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, Optional, Union

import numpy as np


class MetricsWriter:
    """Append-only JSONL sink; every record gets ``event`` and ``ts``.

    ``emit`` is serialized by a lock: the obs heartbeat thread and the
    main thread share one writer, and interleaved lines would corrupt
    the whole trace for every downstream parser."""

    def __init__(self, dest: Union[str, IO]):
        if isinstance(dest, str):
            self._fh: IO = open(dest, "a")
            self._owns = True
        else:
            self._fh = dest
            self._owns = False
        self._lock = threading.Lock()

    def emit(self, event: str, **fields) -> None:
        rec = {"event": event, "ts": round(time.time(), 3)}
        rec.update(fields)
        line = json.dumps(rec, default=_jsonable) + "\n"
        with self._lock:
            self._fh.write(line)
            self._fh.flush()

    def close(self) -> None:
        # under the lock: a straggling emitter (heartbeat beat racing
        # the owner's teardown) must never interleave with the close —
        # the sheeplint lock rule's original true positive
        with self._lock:
            if self._owns:
                self._fh.close()

    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _jsonable(x):
    # np.bool_ first: it is not an np.integer, and bool(np.bool_) is the
    # only faithful JSON mapping (int() would silently change the type)
    if isinstance(x, np.bool_):
        return bool(x)
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, np.generic):
        # remaining numpy scalar subtypes (np.str_, np.bytes_,
        # np.datetime64, ...): item() yields the Python-native value —
        # and when THAT is still not JSON-native (bytes, datetime),
        # degrade to a string rather than re-raising the mid-run
        # TypeError this branch exists to prevent
        v = x.item()
        if isinstance(v, bytes):
            return v.decode("utf-8", "replace")
        try:
            json.dumps(v)
            return v
        except TypeError:
            return str(v)
    raise TypeError(f"not JSON serializable: {type(x)}")


def solve_dispatch_attribution(a: dict, b: dict) -> Optional[dict]:
    """Count x round-cost A/B attribution (VERDICT r5 items 2/7): given
    two measurements of the same build with different dispatch batching
    — dicts with ``wall_s``, ``syncs`` (host->device sync count, the
    ``host_syncs`` diagnostic) and ``rounds`` (``device_rounds``) —
    solve the 2x2 system

        wall = syncs * per_dispatch_s + rounds * per_round_s

    for the per-dispatch overhead and per-round device cost. This is
    what makes the batched-dispatch win provable from dispatch counts
    alone, even on the CPU mesh: the counts are deterministic, only the
    two cost coefficients are hardware-dependent. Returns None when the
    system is degenerate (the two runs have the same sync/round mix —
    nothing to attribute)."""
    det = a["syncs"] * b["rounds"] - b["syncs"] * a["rounds"]
    if det == 0:
        return None
    per_dispatch = (a["wall_s"] * b["rounds"]
                    - b["wall_s"] * a["rounds"]) / det
    per_round = (a["syncs"] * b["wall_s"] - b["syncs"] * a["wall_s"]) / det
    return {"per_dispatch_s": per_dispatch, "per_round_s": per_round}


def device_memory_stats() -> Optional[dict]:
    """Allocator stats of the default device (HBM high-water mark on TPU);
    None where the platform doesn't expose them (e.g. CPU)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return None
        keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size")
        return {k: int(stats[k]) for k in keep if k in stats}
    except Exception:
        return None


def emit_run_metrics(mw: MetricsWriter, res, n_vertices: int,
                     wall_seconds: float, graph: Optional[str] = None) -> None:
    """Standard record set for one partition run: per-phase throughput,
    summary scores, per-part loads, device memory."""
    m = res.total_edges
    mw.emit("run", graph=graph, backend=res.backend, k=res.k,
            n_vertices=int(n_vertices), total_edges=int(m),
            wall_seconds=round(wall_seconds, 4),
            edges_per_sec=round(m / wall_seconds, 1) if wall_seconds > 0 else None)
    for phase, secs in res.phase_times.items():
        mw.emit("phase", phase=phase, seconds=round(secs, 6),
                edges_per_sec=round(m / secs, 1) if secs > 0 else None)
    mw.emit("scores", edge_cut=int(res.edge_cut),
            cut_ratio=float(res.cut_ratio), balance=float(res.balance),
            comm_volume=None if res.comm_volume is None else int(res.comm_volume))
    if res.diagnostics:
        mw.emit("diagnostics", **res.diagnostics)
    loads = np.bincount(res.assignment, minlength=res.k)
    mw.emit("part_loads", loads=loads, max=int(loads.max()),
            min=int(loads.min()))
    mem = device_memory_stats()
    if mem is not None:
        mw.emit("device_memory", **mem)
