"""Structured JSON-lines metrics (SURVEY.md §5 "Metrics / logging").

The reference printed scores to stdout; the rebuild's observability
contract is machine-readable: one JSON object per line, appended to a
file (or any writable handle), covering per-phase throughput, partition
quality, per-part loads, and device-memory high-water marks where the
platform exposes them.

Usage:
    mw = MetricsWriter(path)
    mw.emit("phase", phase="build", seconds=2.3, edges_per_sec=1.2e8)
    mw.close()
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, Optional, Union

import numpy as np


class MetricsWriter:
    """Append-only JSONL sink; every record gets ``event`` and ``ts``.

    ``emit`` is serialized by a lock: the obs heartbeat thread and the
    main thread share one writer, and interleaved lines would corrupt
    the whole trace for every downstream parser."""

    def __init__(self, dest: Union[str, IO]):
        if isinstance(dest, str):
            self._fh: IO = open(dest, "a")
            self._owns = True
        else:
            self._fh = dest
            self._owns = False
        self._lock = threading.Lock()

    def emit(self, event: str, **fields) -> None:
        rec = {"event": event, "ts": round(time.time(), 3)}
        rec.update(fields)
        line = json.dumps(rec, default=_jsonable) + "\n"
        with self._lock:
            self._fh.write(line)
            self._fh.flush()

    def close(self) -> None:
        # under the lock: a straggling emitter (heartbeat beat racing
        # the owner's teardown) must never interleave with the close —
        # the sheeplint lock rule's original true positive
        with self._lock:
            if self._owns:
                self._fh.close()

    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _jsonable(x):
    # np.bool_ first: it is not an np.integer, and bool(np.bool_) is the
    # only faithful JSON mapping (int() would silently change the type)
    if isinstance(x, np.bool_):
        return bool(x)
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, np.generic):
        # remaining numpy scalar subtypes (np.str_, np.bytes_,
        # np.datetime64, ...): item() yields the Python-native value —
        # and when THAT is still not JSON-native (bytes, datetime),
        # degrade to a string rather than re-raising the mid-run
        # TypeError this branch exists to prevent
        v = x.item()
        if isinstance(v, bytes):
            return v.decode("utf-8", "replace")
        try:
            json.dumps(v)
            return v
        except TypeError:
            return str(v)
    raise TypeError(f"not JSON serializable: {type(x)}")


def solve_dispatch_attribution(a: dict, b: dict) -> Optional[dict]:
    """Count x round-cost A/B attribution (VERDICT r5 items 2/7): given
    two measurements of the same build with different dispatch batching
    — dicts with ``wall_s``, ``syncs`` (host->device sync count, the
    ``host_syncs`` diagnostic) and ``rounds`` (``device_rounds``) —
    solve the 2x2 system

        wall = syncs * per_dispatch_s + rounds * per_round_s

    for the per-dispatch overhead and per-round device cost. This is
    what makes the batched-dispatch win provable from dispatch counts
    alone, even on the CPU mesh: the counts are deterministic, only the
    two cost coefficients are hardware-dependent. Returns None when the
    system is degenerate (the two runs have the same sync/round mix —
    nothing to attribute)."""
    det = a["syncs"] * b["rounds"] - b["syncs"] * a["rounds"]
    if det == 0:
        return None
    per_dispatch = (a["wall_s"] * b["rounds"]
                    - b["wall_s"] * a["rounds"]) / det
    per_round = (a["syncs"] * b["wall_s"] - b["syncs"] * a["wall_s"]) / det
    return {"per_dispatch_s": per_dispatch, "per_round_s": per_round}


def residual_attribution(level_cuts, planted_ratios, total_edges: int
                         ) -> Optional[dict]:
    """Attribute a hierarchical build's cut residual against a planted
    optimum, per level (ISSUE 13 — the "where does the 2.5x live"
    question of ROADMAP item 4).

    ``level_cuts``: the ledger's per-level cut counts — edges whose
    endpoint labels first diverge at level d (level 0 = between
    top-level parts, level 1 = within a top part but between subparts,
    ...). ``planted_ratios``: the planted optimum's CUMULATIVE cut
    ratio at each level's grouped k (e.g.
    ``SbmHashStream.planted_cut_ratio(k_d)``), so the planted
    PER-LEVEL increment is the difference of adjacent entries.

    Returns per-level ``excess`` ratios (achieved minus planted, the
    residual each level owns) and the ``dominant`` term, named the way
    the ledger reads: ``level0_fragmentation`` for the top split,
    ``level{d}_misassignment`` below it. None when the inputs don't
    line up."""
    if not level_cuts or not planted_ratios \
            or len(level_cuts) != len(planted_ratios) \
            or not total_edges:
        return None
    levels = []
    prev_planted = 0.0
    for d, (cut, planted_cum) in enumerate(zip(level_cuts,
                                               planted_ratios)):
        achieved = cut / total_edges
        planted_inc = planted_cum - prev_planted
        prev_planted = planted_cum
        levels.append({
            "level": d,
            "name": ("level0_fragmentation" if d == 0
                     else f"level{d}_misassignment"),
            "cut_ratio": round(achieved, 6),
            "planted_ratio": round(planted_inc, 6),
            "excess": round(achieved - planted_inc, 6),
        })
    dominant = max(levels, key=lambda r: r["excess"])
    total_excess = sum(r["excess"] for r in levels)
    return {"levels": levels, "dominant": dominant["name"],
            "dominant_excess": dominant["excess"],
            "total_excess": round(total_excess, 6),
            "dominant_share": round(
                dominant["excess"] / total_excess, 4)
            if total_excess > 0 else None}


def ledger_residual(diagnostics: dict, k_levels, planted_fn,
                    total_edges: int) -> Optional[dict]:
    """:func:`residual_attribution` straight from a result's ledger
    diagnostics: pulls each level's ``cut_level{d}`` row, prices the
    planted grouped optimum at the level's cumulative k via
    ``planted_fn`` (e.g. ``SbmHashStream.planted_cut_ratio``), and
    attributes. The one wiring shared by ``tools/hier_quality.py`` and
    ``tools/quality_regress.py`` — the diagnostics key contract lives
    here, next to the attribution math. None when some level's k does
    not divide the planted blocks (``planted_fn`` raises ValueError):
    no ground truth exists at that grouping."""
    cuts = []
    ratios = []
    kp = 1
    try:
        for depth, kd in enumerate(k_levels):
            kp *= int(kd)
            cuts.append(int(diagnostics.get(f"cut_level{depth}", 0)))
            ratios.append(planted_fn(kp))
    except ValueError:
        return None
    return residual_attribution(cuts, ratios, total_edges)


def device_memory_stats() -> Optional[dict]:
    """Allocator stats of the default device (HBM high-water mark on TPU);
    None where the platform doesn't expose them (e.g. CPU)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return None
        keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size")
        return {k: int(stats[k]) for k in keep if k in stats}
    except Exception:
        return None


def emit_run_metrics(mw: MetricsWriter, res, n_vertices: int,
                     wall_seconds: float, graph: Optional[str] = None) -> None:
    """Standard record set for one partition run: per-phase throughput,
    summary scores, per-part loads, device memory."""
    m = res.total_edges
    mw.emit("run", graph=graph, backend=res.backend, k=res.k,
            n_vertices=int(n_vertices), total_edges=int(m),
            wall_seconds=round(wall_seconds, 4),
            edges_per_sec=round(m / wall_seconds, 1) if wall_seconds > 0 else None)
    for phase, secs in res.phase_times.items():
        mw.emit("phase", phase=phase, seconds=round(secs, 6),
                edges_per_sec=round(m / secs, 1) if secs > 0 else None)
    mw.emit("scores", edge_cut=int(res.edge_cut),
            cut_ratio=float(res.cut_ratio), balance=float(res.balance),
            comm_volume=None if res.comm_volume is None else int(res.comm_volume))
    if res.diagnostics:
        mw.emit("diagnostics", **res.diagnostics)
    loads = np.bincount(res.assignment, minlength=res.k)
    mw.emit("part_loads", loads=loads, max=int(loads.max()),
            min=int(loads.min()))
    mem = device_memory_stats()
    if mem is not None:
        mw.emit("device_memory", **mem)
