"""Platform pinning that survives the TPU plugin's jax pre-import.

In environments where a TPU platform plugin pre-imports jax at
interpreter startup, the JAX_PLATFORMS env var is read before user code
runs and becomes a no-op — merely setting it does NOT stop jax from
initializing (and hanging on) an unreachable accelerator. The only
reliable pin is ``jax.config.update("jax_platforms", ...)`` applied
before the first jax operation. One helper so the workaround lives in
one place (used by bench.py and the CLI; tests/conftest.py does the
same dance inline because it must also set XLA_FLAGS pre-import).
"""

from __future__ import annotations

import os


def pin_platform(platform: str | None = None) -> None:
    """Force ``platform`` (default: the JAX_PLATFORMS env var, if set)
    as the jax platform, in a way that works even when jax was already
    imported by a platform plugin. No-op when neither is given."""
    value = platform or os.environ.get("JAX_PLATFORMS")
    if not value:
        return
    os.environ["JAX_PLATFORMS"] = value
    import jax

    jax.config.update("jax_platforms", value)


def enable_compilation_cache(
        default_dir: str = "/tmp/sheep_jax_cache") -> None:
    """Turn on JAX's persistent compilation cache (config API, because
    the env var is read before user code when a platform plugin
    pre-imports jax). First compiles of the streaming programs cost
    minutes through a remote-device tunnel; repeat runs then start hot.
    Best-effort: jax absent/broken or an old jax without the knobs
    leaves things as-is, with one stderr note (a silently-disabled
    cache re-pays the warm-up with no clue why)."""
    import sys

    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", default_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        print(f"note: persistent compilation cache unavailable: {e}",
              file=sys.stderr)
