"""Device-memory model for the streaming build (VERDICT r1 item 4,
SURVEY.md §7 hard part #2).

All vertex-indexed state is int32[n+1]; the edge chunk contributes
int32[C]-shaped work arrays. The model below counts the worst-case live
set of ``build_chunk_step`` + the elimination fixpoint, which dominates
every other phase (degrees needs 2 tables; scoring needs 1 table + the
chunk). XLA reuses buffers aggressively, so this is an upper bound on
steady-state HBM after warm-up; the real high-water mark is
profiled on hardware (BASELINE.md "HBM budget").
"""

from __future__ import annotations

from sheep_tpu.ops.elim import EXACT_TABLE_BYTES


def build_phase_bytes(n: int, chunk_edges: int, lift_levels: int = 0,
                      descent: str = "auto", dispatch_batch: int = 1,
                      inflight: int = 1, donate: bool = False,
                      h2d_ring: int = 0, resident_bytes: int = 0) -> dict:
    """Estimated peak device bytes for one build_chunk_step.

    The displacement fixpoint (ops/elim.py fold_edges) keeps the carried
    forest in the persistent minp table and only the chunk's C edges
    active, so transients are O(C), not O(V + C). Live set: pos + order
    (persistent, 2 tables), the minp table double-buffered across the
    while_loop carry (2 tables), ~6 C-sized active/work arrays
    (lo/hi/poshi/old_at_lo/now/new_lo), and the lifting table stack
    (exact descent: lift_levels tables bounded by EXACT_TABLE_BYTES;
    stream descent: 1 table).

    ``dispatch_batch`` > 1 (the batched segment dispatch,
    ops/elim.py fold_segments_batch) additionally stages N segments on
    device at once: the raw (N, C, 2) chunk stack plus the oriented
    [N, C] lo/hi blocks — the O(C) transient invariant becomes O(N*C),
    which is exactly what :func:`dispatch_batch_for` sizes N against.

    ``inflight`` > 1 (the asynchronous dispatch pipeline,
    ops/elim.py fold_segments_pipelined) keeps D issued executions'
    staging blocks live at once — staging multiplies by D. ``donate``
    (fold_segments_batch_pos_donated) lets XLA reuse the carried
    table's and each staging block's buffers for the execution outputs
    instead of double-buffering them across the call boundary — it
    credits back one minp table and one staging block's oriented half.

    ``h2d_ring`` (the staged H2D ring, utils/prefetch.H2DRing —
    ISSUE 12) holds up to that many pre-transferred padded blocks in
    device memory awaiting dispatch — ``dispatch_batch`` chunks of
    (C, 2) int32 each per block, so like ``inflight`` it is a
    depth x staging-bytes product. 0 = ring off (device-stream inputs
    synthesize on device and stage nothing; the synchronous path
    uploads in place).

    ``resident_bytes`` (the residency term, ISSUE 20) is the chunk
    bytes the :class:`~sheep_tpu.utils.residency.ResidencyManager`
    currently holds (or budgets) on device — cached chunks are live HBM
    exactly like staging blocks, and a model that ignored them would
    admit builds whose real footprint overflows the instant the cache
    warms. Unlike every other term it is *reclaimable*: the degrade
    ladder spills it before shrinking any dispatch knob (see
    :func:`degraded_dispatch`).
    """
    if lift_levels <= 0:
        lift_levels = max(1, int(n).bit_length())
    table = 4 * (n + 1)
    stack = lift_levels * table
    if descent == "auto":
        descent = "exact" if stack <= EXACT_TABLE_BYTES else "stream"
    lift_bytes = min(stack, EXACT_TABLE_BYTES) if descent == "exact" else table
    persistent = 4 * table  # pos, order, minp x2 (loop carry)
    transient = 6 * 4 * chunk_edges
    # chunk stack (2C words/row) + oriented lo/hi blocks (2C words/row),
    # held once per in-flight execution. The synchronous per-segment
    # driver (dispatch_batch == 1, inflight == 1) stages nothing beyond
    # the counted transients; the pipelined driver stages its [N, C]
    # blocks even at N == 1 (inflight > 1 selects it)
    staging_unit = 4 * 4 * chunk_edges * max(1, dispatch_batch) \
        if dispatch_batch > 1 or inflight > 1 else 0
    staging = staging_unit * max(1, inflight)
    if donate and staging_unit:
        # donated executions alias input buffers into outputs: one minp
        # table (the cross-execution carry copy) and one oriented lo/hi
        # block pair (half a staging unit) come back. Guarded on
        # staging_unit: the synchronous per-segment configuration never
        # runs a donating program, so crediting it there would
        # under-reserve a full table no matter what flag a caller
        # threads through
        persistent -= table
        staging -= staging_unit // 2
    # staged H2D ring: D pre-uploaded (C, 2) int32 blocks (x batch
    # chunks each) live in HBM between transfer and dispatch
    ring_bytes = 4 * 2 * chunk_edges * max(1, dispatch_batch) \
        * max(0, h2d_ring)
    resident = max(0, int(resident_bytes))
    total = persistent + transient + staging + ring_bytes + lift_bytes \
        + resident
    return {
        "persistent_bytes": persistent,
        "transient_bytes": transient,
        "staging_bytes": staging,
        "h2d_ring_bytes": ring_bytes,
        "lift_bytes": lift_bytes,
        "resident_bytes": resident,
        "descent": descent,
        "total_bytes": total,
    }


def dispatch_batch_for(hbm_bytes: int, n: int, chunk_edges: int,
                       cap: int = 16, inflight: int = 1,
                       donate: bool = False, h2d_ring: int = 0) -> int:
    """Largest power-of-two dispatch batch N in [1, cap] whose staged
    build phase fits ``hbm_bytes`` — the ``--dispatch-batch 0`` (auto)
    sizing rule. Power-of-two N keeps the set of compiled batch-program
    shapes logarithmic, like every other buffer-sizing rule here.
    ``inflight``/``donate``/``h2d_ring`` thread the in-flight
    pipeline's staging multiplier, the donation credit and the staged
    H2D ring into the model, so a deeper pipeline (or ring) auto-sizes
    to a proportionally smaller N."""
    best = 1
    nb = 2
    while nb <= cap:
        if build_phase_bytes(n, chunk_edges, dispatch_batch=nb,
                             inflight=inflight, donate=donate,
                             h2d_ring=h2d_ring)["total_bytes"] > hbm_bytes:
            break
        best = nb
        nb *= 2
    return best


def degraded_dispatch(n: int, chunk_edges: int, dispatch_batch: int,
                      inflight: int, donate: bool = False,
                      h2d_ring=None, spillable_bytes: int = 0):
    """One RESOURCE_EXHAUSTED degradation step for the dispatch drivers
    (ISSUE 9): halve ``dispatch_batch``, ``inflight`` — or, when the
    caller runs a staged H2D ring (``h2d_ring`` given as an int >= 1,
    ISSUE 12), the ring depth — whichever frees MORE modeled bytes per
    the build-phase HBM model above. Returns the new
    ``(dispatch_batch, inflight)`` pair (legacy callers, ``h2d_ring``
    omitted) or the ``(dispatch_batch, inflight, h2d_ring)`` triple,
    or ``None`` when every knob is already 1 (nothing left to shed;
    the caller falls back to a plain retry, then to the
    checkpoint/kill+resume contract).

    **Spill-before-shrink** (ISSUE 20): when the caller holds evictable
    resident chunks (``spillable_bytes`` > 0), the ladder's FIRST rung
    is spilling them — cached chunks are a pure latency optimization
    whose modeled bytes come back for free, while halving a dispatch
    knob permanently costs overlap for the rest of the run. The step is
    then ``("spill", dispatch_batch, inflight[, h2d_ring])``: the knobs
    come back *unchanged* and the caller (utils/retry.degrade_dispatch
    with a residency manager) performs the actual eviction. Only with
    nothing left to spill does the ladder fall through to halving.

    Reusing :func:`build_phase_bytes` instead of a fixed halving order
    keeps the degrade schedule consistent with the auto-sizing rule
    (:func:`dispatch_batch_for`): the knob that the model says holds the
    most staging is the knob an OOM most plausibly indicts."""
    batch, depth = max(1, int(dispatch_batch)), max(1, int(inflight))
    ring = None if h2d_ring is None else max(1, int(h2d_ring))
    if spillable_bytes > 0:
        step = ("spill", batch, depth)
        return step + (ring,) if ring is not None else step
    if batch <= 1 and depth <= 1 and (ring is None or ring <= 1):
        return None

    def total(b, d, r):
        return build_phase_bytes(n, chunk_edges, dispatch_batch=b,
                                 inflight=d, donate=donate,
                                 h2d_ring=r or 0)["total_bytes"]

    r0 = ring or 0
    cand = []
    if batch > 1:
        cand.append((total(batch // 2, depth, r0),
                     (batch // 2, depth, r0)))
    if depth > 1:
        cand.append((total(batch, depth // 2, r0),
                     (batch, depth // 2, r0)))
    if ring is not None and ring > 1:
        cand.append((total(batch, depth, ring // 2),
                     (batch, depth, ring // 2)))
    # smallest modeled footprint wins; ties prefer halving the batch
    # (listed first), which keeps the pipeline depth — and its overlap —
    # alive longest
    best = min(cand, key=lambda c: c[0])[1]
    return best if ring is not None else best[:2]


def max_vertices_for(hbm_bytes: int, chunk_edges: int) -> int:
    """Largest power-of-2 vertex count whose build fits ``hbm_bytes``."""
    v = 1
    while build_phase_bytes(2 * v, chunk_edges)["total_bytes"] <= hbm_bytes:
        v *= 2
    return v
