"""Fault injection (SURVEY.md §5 "Failure detection / fault injection").

A test hook that kills the pipeline mid-stream, exercising the
checkpoint/resume recovery path. Enabled via the environment variable

    SHEEP_FAULT_INJECT="<phase>:<chunks>"     e.g. "build:3"

which makes the named phase raise :class:`InjectedFault` after processing
that many chunks. The recovery tests (tests/test_checkpoint.py) inject a
fault, catch it, then resume from the last checkpoint and assert the final
partition is identical to an uninterrupted run — the mergeable-forest
property that makes chunk-level restart sound.
"""

from __future__ import annotations

import os

ENV_VAR = "SHEEP_FAULT_INJECT"


class InjectedFault(RuntimeError):
    """Raised by the injection hook; never raised in production runs."""


def _parse(spec: str):
    phase, _, count = spec.partition(":")
    try:
        return phase, int(count)
    except ValueError:
        raise ValueError(f"bad {ENV_VAR} spec {spec!r}; want '<phase>:<int>'")


def maybe_fail(phase: str, chunks_done: int) -> None:
    """Raise InjectedFault iff the env hook targets this phase and count."""
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return
    target_phase, target_count = _parse(spec)
    if phase == target_phase and chunks_done >= target_count:
        raise InjectedFault(
            f"injected fault in phase {phase!r} after {chunks_done} chunks")
