"""Fault injection (SURVEY.md §5 "Failure detection / fault injection").

A test hook that injects faults into the pipeline mid-stream, exercising
the checkpoint/resume recovery path (PR 8) and the in-process
fault-tolerance layer (ISSUE 9: utils/retry.py). Enabled via the
environment variable ``SHEEP_FAULT_INJECT``, three grammars:

**Kill at a deterministic point (legacy, PR-8 drills)**::

    SHEEP_FAULT_INJECT="<phase>:<count>"      e.g. "build:3"

makes the named phase raise :class:`InjectedFault` after processing that
many chunks — and on EVERY later call, so a caught-and-ignored fault
cannot silently continue (the recovery tests catch it, clear the env,
then resume from the last checkpoint). ``<phase>`` may also name an
enclosing :func:`scope` ("level0:3", "level:1" — the hierarchy
granularities of PR 8).

**Typed fault at a deterministic point (ISSUE 9 pinned tests)**::

    SHEEP_FAULT_INJECT="<kind>@<phase>:<count>[:<shots>]"
                                                   e.g. "oom@dispatch:2"

raises the kind's exception at the first call where the count is
reached, at most ``shots`` times per process (default 1 — unlike the
kill grammar these faults are *handled* in-process, and re-raising
forever at the same point would defeat the retry the injection exists
to exercise; shots > 1 drills REPEATED faults, e.g. two OOMs forcing
two degradation steps). Kinds:

    oom      :class:`InjectedResourceExhausted`  (fault_class=resource)
    device   :class:`InjectedDeviceLoss`         (fault_class=device_loss)
    read     :class:`InjectedReadError`          (OSError; transient)
    kill     :class:`InjectedFault`              (fatal — like legacy)
    stall    no exception: sleeps ``STALL_S`` seconds at the point — the
             slow-peer emulation that ages heartbeat/watchdog clocks
             without wedging the test process

**Randomized chaos schedule (tools/chaos_soak.py)**::

    SHEEP_FAULT_INJECT="chaos:<seed>[:<budget>[:<rate>]]"

arms a seeded RNG over every injection point: each point draws, and
with probability ``rate`` (default 0.08) injects one fault drawn from
the kinds that point declared, until ``budget`` faults (default 2) have
fired. Deterministic given the seed and the (deterministic) call
sequence; each injection emits a ``chaos_inject`` trace event so the
soak runner can audit what actually fired.

Phase names are injection POINTS, not just streaming phases: the
batched dispatch drivers report phase "dispatch" per issued execution,
edge readers report phase "read" per physical read, and the classic
per-chunk sites keep their phase names ("degrees"/"build"/"score").
"""

from __future__ import annotations

import os
import random
from contextlib import contextmanager
from typing import Dict, List, Tuple

ENV_VAR = "SHEEP_FAULT_INJECT"

# enclosing execution scopes (e.g. "level0" while hierarchy's level-0
# flat partition streams); module-level is fine — injection is a
# single-threaded test hook, never armed in production runs
_SCOPES: List[str] = []

# shots-consumed state for the typed grammar, keyed by spec; re-armed
# on an observed env TRANSITION (maybe_fail sees a different value than
# last time, including unset) and by the explicit reset() test helper —
# keying alone would leave a re-set identical spec permanently consumed
_CONSUMED: Dict[str, int] = {}

# chaos schedule state, keyed by spec (seed change -> fresh schedule;
# same transition/reset re-arming as _CONSUMED)
_CHAOS: Dict[str, dict] = {}

_LAST_SPEC: List = [None]


def reset() -> None:
    """Forget all consumed-shot and chaos-schedule state, re-arming
    whatever spec is (or will be) in the environment. Test helper —
    production runs arm one spec per process and never need it."""
    _CONSUMED.clear()
    _CHAOS.clear()
    _LAST_SPEC[0] = None

CHAOS_DEFAULT_BUDGET = 2
CHAOS_DEFAULT_RATE = 0.08


class InjectedFault(RuntimeError):
    """Kill-style injected fault; never raised in production runs. The
    retry layer classifies it FATAL — it exists to kill the process so
    the checkpoint/resume drills stay honest."""

    fault_class = "fatal"


class InjectedResourceExhausted(RuntimeError):
    """Injected RESOURCE_EXHAUSTED-class fault: same retry-layer path as
    a real XLA 'RESOURCE_EXHAUSTED: ...' allocation failure."""

    fault_class = "resource"


class InjectedDeviceLoss(RuntimeError):
    """Injected device-loss-class fault: snapshot + reinit + resume."""

    fault_class = "device_loss"


class InjectedReadError(OSError):
    """Injected transient read failure (an OSError, like the real
    thing): the edgestream's bounded read retry absorbs it."""

    fault_class = "transient"


_KINDS = {
    "kill": InjectedFault,
    "oom": InjectedResourceExhausted,
    "device": InjectedDeviceLoss,
    "read": InjectedReadError,
    "stall": None,  # sleeps instead of raising (slow-peer emulation)
}

STALL_S = 0.5


@contextmanager
def scope(name: str):
    """Mark the dynamic extent of a named execution scope; a spec whose
    phase names the scope fires inside ANY streaming phase running
    under it."""
    _SCOPES.append(name)
    try:
        yield
    finally:
        _SCOPES.pop()


def _parse(spec: str) -> Tuple[str, str, int, int]:
    """spec -> (kind, phase, count, shots); kind '' = legacy grammar."""
    head, _, count = spec.partition(":")
    kind, at, phase = head.partition("@")
    if not at:
        kind, phase = "", head
    elif kind not in _KINDS:
        raise ValueError(f"bad {ENV_VAR} kind {kind!r}; "
                         f"want one of {sorted(_KINDS)}")
    count, _, shots = count.partition(":")
    try:
        return kind, phase, int(count), int(shots) if shots else 1
    except ValueError:
        raise ValueError(f"bad {ENV_VAR} spec {spec!r}; want "
                         f"'[kind@]<phase>:<int>[:<shots>]' or "
                         f"'chaos:<seed>'")


def _raise_kind(kind: str, msg: str):
    if kind == "stall":
        import time

        time.sleep(STALL_S)
        return
    exc_type = _KINDS[kind]
    if kind == "oom":
        # carry the real-world status string so pattern-based
        # classification (not just the fault_class attr) is exercised
        raise exc_type(f"RESOURCE_EXHAUSTED (injected): {msg}")
    raise exc_type(f"injected {kind} fault: {msg}")


def _chaos_state(spec: str) -> dict:
    st = _CHAOS.get(spec)
    if st is None:
        parts = spec.split(":")
        try:
            seed = int(parts[1])
            budget = int(parts[2]) if len(parts) > 2 \
                else CHAOS_DEFAULT_BUDGET
            rate = float(parts[3]) if len(parts) > 3 \
                else CHAOS_DEFAULT_RATE
        except (IndexError, ValueError):
            raise ValueError(f"bad {ENV_VAR} spec {spec!r}; want "
                             f"'chaos:<seed>[:<budget>[:<rate>]]'")
        st = _CHAOS[spec] = {"rng": random.Random(seed),
                             "budget": budget, "rate": rate,
                             "points": 0, "injected": 0}
    return st


def _maybe_chaos(spec: str, phase: str, kinds: Tuple[str, ...]) -> None:
    st = _chaos_state(spec)
    st["points"] += 1
    if st["injected"] >= st["budget"]:
        return
    # draw even when this point offers no kinds we can pick (keeps the
    # point sequence — and thus the schedule — stable as call sites
    # gain or lose kind capabilities)
    r = st["rng"].random()
    pick = st["rng"].randrange(len(kinds)) if kinds else 0
    if r >= st["rate"] or not kinds:
        return
    kind = kinds[pick]
    st["injected"] += 1
    from sheep_tpu import obs

    obs.event("chaos_inject", kind=kind, phase=phase,
              point=st["points"], injected=st["injected"],
              budget=st["budget"])
    _raise_kind(kind, f"chaos point {st['points']} in phase {phase!r}")


def maybe_fail(phase: str, chunks_done: int,
               kinds: Tuple[str, ...] = ("kill",)) -> None:
    """Injection point: raise per the armed ``SHEEP_FAULT_INJECT`` spec
    iff it targets this phase (or an enclosing scope) and count.
    ``kinds`` declares which fault kinds this call site can absorb —
    chaos schedules only draw from them (a reader can't OOM the device;
    a dispatch loop can't tear a file read)."""
    spec = os.environ.get(ENV_VAR)
    if spec != _LAST_SPEC[0]:
        # env transition observed: a newly-(re)armed spec starts with
        # fresh shot/schedule state
        _LAST_SPEC[0] = spec
        if spec:
            _CONSUMED.pop(spec, None)
            _CHAOS.pop(spec, None)
    if not spec:
        return
    if spec.startswith("chaos:"):
        _maybe_chaos(spec, phase, kinds)
        return
    kind, target_phase, target_count, shots = _parse(spec)
    if target_phase != phase and target_phase not in _SCOPES:
        return
    if chunks_done < target_count:
        return
    where = (f"phase {phase!r}"
             + (f" (scope {target_phase!r})" if target_phase != phase
                else "")
             + f" after {chunks_done} chunks")
    if not kind:  # legacy kill grammar: raises on every later call too
        raise InjectedFault(f"injected fault in {where}")
    if _CONSUMED.get(spec, 0) >= shots:  # typed grammar: bounded shots
        return
    _CONSUMED[spec] = _CONSUMED.get(spec, 0) + 1
    from sheep_tpu import obs

    obs.event("fault_inject", kind=kind, phase=phase,
              chunks_done=int(chunks_done))
    _raise_kind(kind, where)
