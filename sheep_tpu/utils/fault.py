"""Fault injection (SURVEY.md §5 "Failure detection / fault injection").

A test hook that kills the pipeline mid-stream, exercising the
checkpoint/resume recovery path. Enabled via the environment variable

    SHEEP_FAULT_INJECT="<phase>:<count>"      e.g. "build:3"

which makes the named phase raise :class:`InjectedFault` after processing
that many chunks. The recovery tests (tests/test_checkpoint.py) inject a
fault, catch it, then resume from the last checkpoint and assert the final
partition is identical to an uninterrupted run — the mergeable-forest
property that makes chunk-level restart sound.

Hierarchy phases (ISSUE 8): ``<phase>`` may also name an enclosing
:func:`scope` instead of the streaming phase itself —

    SHEEP_FAULT_INJECT="level0:3"   # inside hierarchy level 0, after 3
                                    # chunks of whatever inner phase is
                                    # streaming (the flat partition of
                                    # level 0 runs under scope "level0")
    SHEEP_FAULT_INJECT="level:1"    # after 1 completed level-boundary
                                    # (hierarchy.py reports each part's
                                    # completion as phase "level")

so kill+resume drills can target the hierarchical driver at both of its
recovery granularities (chunk-level inside level 0, level-boundary for
the recursion).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import List

ENV_VAR = "SHEEP_FAULT_INJECT"

# enclosing execution scopes (e.g. "level0" while hierarchy's level-0
# flat partition streams); module-level is fine — injection is a
# single-threaded test hook, never armed in production runs
_SCOPES: List[str] = []


class InjectedFault(RuntimeError):
    """Raised by the injection hook; never raised in production runs."""


@contextmanager
def scope(name: str):
    """Mark the dynamic extent of a named execution scope; a spec whose
    phase names the scope fires inside ANY streaming phase running
    under it."""
    _SCOPES.append(name)
    try:
        yield
    finally:
        _SCOPES.pop()


def _parse(spec: str):
    phase, _, count = spec.partition(":")
    try:
        return phase, int(count)
    except ValueError:
        raise ValueError(f"bad {ENV_VAR} spec {spec!r}; want '<phase>:<int>'")


def maybe_fail(phase: str, chunks_done: int) -> None:
    """Raise InjectedFault iff the env hook targets this phase (or an
    enclosing scope) and count."""
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return
    target_phase, target_count = _parse(spec)
    if target_phase != phase and target_phase not in _SCOPES:
        return
    if chunks_done >= target_count:
        raise InjectedFault(
            f"injected fault in phase {phase!r}"
            + (f" (scope {target_phase!r})" if target_phase != phase else "")
            + f" after {chunks_done} chunks")
