"""Host-I/O / device-compute overlap (SURVEY.md §2 parallelism table, PP
row: "double-buffering").

A bounded background-thread prefetcher for the streaming loops: while the
device folds chunk i, the worker thread reads + parses + pads chunk i+1
(file reads, np.fromfile and the ctypes text parser all release the GIL,
so the overlap is real). Depth 2 is double-buffering — one item in flight
on the device, one ready on host — which makes the build phase wall
approximately max(io, compute) instead of their sum (VERDICT r1 item 6).

The wrapper preserves item order exactly (checkpoint chunk indices and
fault-injection counters are unaffected) and propagates worker exceptions
to the consumer at the point of `next()` — with the ORIGINAL worker-side
traceback attached, so the consumer's log names the failing reader frame
rather than this module's re-raise. A worker that dies without
delivering its termination sentinel (killed out-of-band) surfaces as a
RuntimeError at the next `next()` instead of an eternal blocking get,
and ``close()`` joins with a timeout, so neither path can hang the
consumer's unwind (ISSUE 9 satellite; regression-tested with an
injected reader fault).

Lifecycle (ISSUE 4 satellite): :func:`prefetch` returns a
:class:`Prefetcher`, an iterator with an explicit :meth:`Prefetcher.close`
that CANCELS the worker — sets the stop event, drains the bounded queue
so a worker blocked on a full ``put`` wakes immediately, and joins the
thread. Consumers that may abandon the stream mid-iteration (the
in-flight dispatch pipeline's discard/backstop paths, exception unwinds)
call it from a ``finally`` so the worker (and whatever file handle or
device transfer it holds) is released deterministically instead of
whenever the GC finalizes a half-consumed generator. Iterating after
``close`` raises ``StopIteration``; ``close`` is idempotent and also runs
on ``with``-exit and finalization.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Iterable, Iterator, TypeVar

T = TypeVar("T")

_END = object()

#: :meth:`Prefetcher.poll_nowait` return when nothing is queued yet —
#: distinct from every real item AND from stream end (StopIteration)
NOT_READY = object()


class _Raised:
    __slots__ = ("exc", "tb")

    def __init__(self, exc: BaseException):
        self.exc = exc
        self.tb = exc.__traceback__  # worker-side frames, re-attached
        #                              at the consumer's re-raise


class Prefetcher(Iterator[T]):
    """Background-thread iterator over ``iterable`` keeping up to
    ``depth`` items ready ahead of the consumer (see module docstring
    for the close/cancel contract)."""

    def __init__(self, iterable: Iterable[T], depth: int = 2):
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        from sheep_tpu import obs

        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._closed = False
        self._done = False
        # flight-recorder attribution (ISSUE 11): the worker inherits
        # the job context of the thread that CREATED it (thread-locals
        # don't cross threads), so read faults / retries emitted while
        # pre-reading a served job's chunks land in that job's ring,
        # not the global one
        self._flight_job = obs.flight_job()
        self._thread = threading.Thread(
            target=self._worker, args=(iterable,), daemon=True,
            name="sheep-prefetch")
        self._thread.start()

    def _put_until_stop(self, item) -> bool:
        """Bounded put that gives up when the consumer signalled stop;
        returns True when the item was enqueued."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self, iterable) -> None:
        from sheep_tpu import obs

        with obs.flight_job_context(self._flight_job):
            try:
                for item in iterable:
                    if not self._put_until_stop(item):
                        return
                    if self._stop.is_set():
                        return
            except BaseException as e:  # delivered to the consumer
                self._put_until_stop(_Raised(e))
                return
            self._put_until_stop(_END)

    def __iter__(self) -> "Prefetcher[T]":
        return self

    def __next__(self) -> T:
        if self._closed or self._done:
            raise StopIteration
        while True:
            try:
                item = self._q.get(timeout=0.5)
                break
            except queue.Empty:
                # liveness guard (ISSUE 9 satellite): a worker that died
                # without delivering its end/exception sentinel (thread
                # killed out-of-band, sentinel put failed) must surface
                # as a diagnosis at the consumer, not an eternal
                # blocking get
                if not self._thread.is_alive():
                    # the worker may have delivered its final item or
                    # sentinel BETWEEN the get timeout and the
                    # liveness check — drain once before declaring it
                    # sentinelless, or a legitimate last chunk (or the
                    # real worker exception) would be replaced by the
                    # bogus died-without diagnosis
                    try:
                        item = self._q.get_nowait()
                        break
                    except queue.Empty:
                        pass
                    self._done = True
                    self._stop.set()
                    raise RuntimeError(
                        "prefetch worker died without delivering a "
                        "result or its termination sentinel")
        if item is _END:
            self._done = True
            self._stop.set()
            raise StopIteration
        if isinstance(item, _Raised):
            self._done = True
            self._stop.set()
            # re-raise with the ORIGINAL worker-side traceback attached
            # (explicit, so the frames that name the failing reader
            # survive even if something cleared __traceback__ in
            # transit) — the consumer's log points at the real fault
            raise item.exc.with_traceback(item.tb)
        return item

    def poll_nowait(self):
        """Non-blocking probe: the next item when one is already
        queued, the module sentinel :data:`NOT_READY` otherwise.
        Stream end and worker exceptions surface exactly as in
        :meth:`__next__` (StopIteration / the original error). This is
        the opportunistic-refill hook of :class:`H2DRing`: the ring
        tops itself up with whatever the worker has ready without ever
        blocking the consumer on the producer thread."""
        if self._closed or self._done:
            raise StopIteration
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            return NOT_READY
        if item is _END:
            self._done = True
            self._stop.set()
            raise StopIteration
        if isinstance(item, _Raised):
            self._done = True
            self._stop.set()
            raise item.exc.with_traceback(item.tb)
        return item

    def close(self, timeout: float = 5.0) -> None:
        """Cancel the worker: signal stop, drain the queue (a worker
        blocked on the full bounded queue wakes within one put poll),
        and join the thread. Idempotent; safe from ``finally`` blocks.
        A worker stuck inside the underlying iterable longer than
        ``timeout`` is abandoned (it is a daemon thread) rather than
        hanging the caller's unwind."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so a put-blocked worker observes the stop event promptly
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=timeout)
        # the worker may have completed one last put between the drain
        # and its stop check; leave nothing referenced
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "Prefetcher[T]":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def __del__(self):  # best-effort backstop; explicit close preferred
        try:
            self.close(timeout=0.0)
        except Exception:
            pass


def prefetch(iterable: Iterable[T], depth: int = 2) -> Prefetcher[T]:
    """Iterate ``iterable`` on a background thread, keeping up to
    ``depth`` items ready ahead of the consumer.

    Returns a :class:`Prefetcher`; call :meth:`Prefetcher.close` (or use
    ``with``) when abandoning it before exhaustion — early consumer exit
    otherwise stops the worker on the GC backstop only.
    """
    return Prefetcher(iterable, depth=depth)


# ---------------------------------------------------------------------------
# staged H2D ring (ISSUE 12 tentpole, leg b). The prefetcher above hides
# host READ latency; the host->device transfer itself still ran
# synchronously in the dispatch chain (`jnp.asarray(padded)` issued at
# the exact moment the driver needed the block). jax transfers are
# asynchronous once ISSUED, so the only thing needed to take H2D off the
# critical path is issuing each block's device_put D blocks ahead of its
# consumption — while the device folds block i, the transfers for blocks
# i+1..i+D are already in flight.
# ---------------------------------------------------------------------------


def _block_bytes(block) -> int:
    """Host bytes of one staged block (a single array or a list/tuple of
    them — the grouped staging of the batched dispatch)."""
    if isinstance(block, (list, tuple)):
        return sum(_block_bytes(b) for b in block)
    return int(getattr(block, "nbytes", 0))


class H2DRing:
    """Bounded ring of staged host->device transfers over an iterator of
    PRE-PADDED host blocks (a ``(C, 2)`` chunk, or a list of them — any
    pytree ``jax.device_put`` accepts).

    Keeps up to ``depth`` blocks' transfers issued AHEAD of the
    consumer, preserving order exactly; each yielded device array is
    bit-identical to what ``jnp.asarray`` of the same host block yields,
    so every consumer stays on the fixpoint-uniqueness contract.
    Refills are OPPORTUNISTIC when the source is a :class:`Prefetcher`
    (:meth:`Prefetcher.poll_nowait` — the ring never blocks the consumer
    on the producer thread while it still holds staged blocks); plain
    iterables refill eagerly.

    Counters, accumulated UNROUNDED into ``stats`` (read-time rounding,
    like every ``*_ms`` counter):

    - ``h2d_staged_ms``   wall spent *issuing* ahead-of-need transfers
      (async issue cost — the transfer itself overlaps device compute)
    - ``h2d_blocked_ms``  wall the consumer spent waiting for a block
      the ring did not have staged (mid-stream underrun — exactly the
      synchronous-upload tax this class removes; ~0 at depth >= 2 with
      a keeping-up producer, the ``device_gap_ms`` pattern). The
      startup fill is attributed to staged, not blocked: before the
      first block there is no device work to overlap, the same
      convention ``device_gap_ms`` uses for the first dispatch.
    - ``h2d_staged_bytes``  host bytes that crossed through the ring
    - ``h2d_ring_depth``    the resolved depth (gauge)

    Lifecycle mirrors :class:`Prefetcher`: ``close()`` drops the staged
    device references (releasing their HBM — the drain the
    checkpoint/fault contract needs when a driver abandons the stream
    mid-flight) and closes a closeable source; idempotent, ``with``
    supported, iteration after close raises StopIteration.
    """

    def __init__(self, source, depth: int = 2, stats=None):
        if depth < 1:
            raise ValueError("h2d ring depth must be >= 1")
        self.depth = int(depth)
        self._src = source if hasattr(source, "__next__") \
            else iter(source)
        self._poll = getattr(self._src, "poll_nowait", None)
        self._ring: deque = deque()
        self._stats = stats if stats is not None else {}
        self._stats.setdefault("h2d_staged_ms", 0.0)
        self._stats.setdefault("h2d_blocked_ms", 0.0)
        self._stats.setdefault("h2d_staged_bytes", 0)
        self._stats["h2d_ring_depth"] = self.depth
        self._exhausted = False
        self._closed = False
        self._started = False

    def _issue(self, block) -> None:
        """Issue one block's (async) transfer and append it."""
        import jax

        self._stats["h2d_staged_bytes"] += _block_bytes(block)
        self._ring.append(jax.device_put(block))

    def _fill(self, want: int, may_block: bool) -> None:
        """Stage transfers until the ring holds ``want`` blocks or the
        source has nothing (ready, when non-blocking) left."""
        while len(self._ring) < want and not self._exhausted:
            try:
                if self._poll is not None and not may_block:
                    block = self._poll()
                    if block is NOT_READY:
                        return
                else:
                    block = next(self._src)
            except StopIteration:
                self._exhausted = True
                return
            self._issue(block)
            may_block = False  # at most one blocking pull per fill

    def __iter__(self) -> "H2DRing":
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if not self._ring and not self._exhausted:
            # underrun (or startup): the consumer waits for host + issue
            # in its critical path — the tax the ring exists to hide
            t0 = time.perf_counter()
            self._fill(1, may_block=True)
            key = "h2d_blocked_ms" if self._started else "h2d_staged_ms"
            self._stats[key] += (time.perf_counter() - t0) * 1e3
        if not self._ring:
            raise StopIteration
        self._started = True
        out = self._ring.popleft()
        # top back up to depth off the critical path: transfers are
        # issued (async) now, so they run under the consumer's device
        # work on `out`; only the issue cost lands in staged_ms
        t0 = time.perf_counter()
        self._fill(self.depth, may_block=self._poll is None)
        self._stats["h2d_staged_ms"] += (time.perf_counter() - t0) * 1e3
        return out

    def close(self) -> None:
        """Drop staged device references and close a closeable source.
        Idempotent; safe from ``finally`` blocks."""
        if self._closed:
            return
        self._closed = True
        self._ring.clear()
        close = getattr(self._src, "close", None)
        if close is not None:
            close()

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "H2DRing":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def prefetch_batched(iterable: Iterable[T], batch: int,
                     depth: int = 2) -> Prefetcher[list]:
    """Group ``iterable`` into lists of up to ``batch`` items on the
    prefetch worker thread — the staging primitive of the batched
    segment dispatch: all N chunks of the NEXT enlarged device program
    are read + parsed + padded while the device runs the current one
    (``depth`` counts staged *groups*, so depth 2 keeps up to 2N items
    in flight). Order, completeness, exception propagation, early
    consumer exit and :meth:`Prefetcher.close` behave exactly as
    :func:`prefetch`; the final group may be shorter than ``batch``."""
    if batch < 1:
        raise ValueError("prefetch batch must be >= 1")

    def grouped():
        buf: list = []
        for item in iterable:
            buf.append(item)
            if len(buf) == batch:
                yield buf
                buf = []
        if buf:
            yield buf

    return prefetch(grouped(), depth=depth)
