"""Host-I/O / device-compute overlap (SURVEY.md §2 parallelism table, PP
row: "double-buffering").

A bounded background-thread prefetcher for the streaming loops: while the
device folds chunk i, the worker thread reads + parses + pads chunk i+1
(file reads, np.fromfile and the ctypes text parser all release the GIL,
so the overlap is real). Depth 2 is double-buffering — one item in flight
on the device, one ready on host — which makes the build phase wall
approximately max(io, compute) instead of their sum (VERDICT r1 item 6).

The wrapper preserves item order exactly (checkpoint chunk indices and
fault-injection counters are unaffected) and propagates worker exceptions
to the consumer at the point of `next()`.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, TypeVar

T = TypeVar("T")

_END = object()


class _Raised:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch(iterable: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Iterate ``iterable`` on a background thread, keeping up to ``depth``
    items ready ahead of the consumer.

    Early consumer exit (break / GeneratorExit) stops the worker promptly:
    the worker checks a stop event around every bounded put.
    """
    if depth < 1:
        raise ValueError("prefetch depth must be >= 1")
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put_until_stop(item) -> bool:
        """Bounded put that gives up when the consumer signalled stop;
        returns True when the item was enqueued."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in iterable:
                if not put_until_stop(item):
                    return
        except BaseException as e:  # delivered to the consumer
            put_until_stop(_Raised(e))
            return
        put_until_stop(_END)

    t = threading.Thread(target=worker, daemon=True, name="sheep-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, _Raised):
                raise item.exc
            yield item
    finally:
        stop.set()


def prefetch_batched(iterable: Iterable[T], batch: int,
                     depth: int = 2) -> Iterator[list]:
    """Group ``iterable`` into lists of up to ``batch`` items on the
    prefetch worker thread — the staging primitive of the batched
    segment dispatch: all N chunks of the NEXT enlarged device program
    are read + parsed + padded while the device runs the current one
    (``depth`` counts staged *groups*, so depth 2 keeps up to 2N items
    in flight). Order, completeness, exception propagation and early
    consumer exit behave exactly as :func:`prefetch`; the final group
    may be shorter than ``batch``."""
    if batch < 1:
        raise ValueError("prefetch batch must be >= 1")

    def grouped():
        buf: list = []
        for item in iterable:
            buf.append(item)
            if len(buf) == batch:
                yield buf
                buf = []
        if buf:
            yield buf

    return prefetch(grouped(), depth=depth)
