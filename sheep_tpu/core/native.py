"""ctypes loader for the native CPU core (sheep_tpu/core/csrc).

pybind11 is not available in this environment, so the C++ core exposes a
plain C ABI over caller-allocated numpy buffers. The library is built
lazily with make on first use; failure to build leaves the ``cpu`` backend
unregistered (callers fall back to ``pure``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")
_SO = os.path.join(_CSRC, "libsheep_core.so")
_lib: Optional[ctypes.CDLL] = None

_i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(dtype=np.uint32, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")


class _f64p_or_null(_f64p):
    """float64 ndpointer that also accepts None (passed as NULL) — for
    C functions whose array argument is optional, e.g. unit weights."""

    @classmethod
    def from_param(cls, obj):
        if obj is None:
            return None
        return _f64p.from_param(obj)


def _build() -> None:
    src = os.path.join(_CSRC, "sheep_core.cpp")
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(src):
        return
    subprocess.run(
        ["make", "-C", _CSRC],
        check=True,
        capture_output=True,
        text=True,
    )


def load() -> ctypes.CDLL:
    """Build if needed and load the native library (cached)."""
    global _lib
    if _lib is not None:
        return _lib
    _build()
    lib = ctypes.CDLL(_SO)

    lib.sheep_core_abi_version.restype = ctypes.c_int64
    if lib.sheep_core_abi_version() != 1:
        raise RuntimeError("libsheep_core ABI mismatch; run make clean")

    c_i64 = ctypes.c_int64
    lib.sheep_degrees.argtypes = [_i64p, c_i64, c_i64, _i64p]
    lib.sheep_elim_order.argtypes = [_i64p, c_i64, _i64p]
    lib.sheep_build_elim_tree.argtypes = [_i64p, c_i64, _i64p, c_i64, _i64p]
    lib.sheep_merge_trees.argtypes = [_i64p, _i64p, _i64p, c_i64]
    lib.sheep_tree_split.argtypes = [_i64p, _i64p, _f64p_or_null, c_i64,
                                     c_i64, ctypes.c_double, _i32p]
    lib.sheep_score_chunk.argtypes = [_i64p, c_i64, _i32p, c_i64,
                                      ctypes.POINTER(c_i64), ctypes.POINTER(c_i64)]
    lib.sheep_cut_pairs.argtypes = [_i64p, c_i64, _i32p, c_i64, c_i64, _i64p]
    lib.sheep_cut_pairs.restype = c_i64
    lib.sheep_parse_text.argtypes = [ctypes.c_char_p, c_i64, _i64p, c_i64,
                                     ctypes.POINTER(c_i64)]
    lib.sheep_parse_text.restype = c_i64
    lib.sheep_rmat_hash_range.argtypes = [c_i64, c_i64, c_i64, _u32p, _u32p,
                                          ctypes.c_uint32, ctypes.c_uint32,
                                          ctypes.c_uint32, _i64p]
    if hasattr(lib, "sheep_sbm_hash_range"):
        # round-4 symbol; a pre-round-4 .so (stale build) simply keeps
        # the numpy path (generators.sbm_hash_range checks this hasattr)
        lib.sheep_sbm_hash_range.argtypes = [c_i64, c_i64, _u32p, _u32p,
                                             ctypes.c_uint32, c_i64, c_i64,
                                             _i64p]
    _lib = lib
    return lib


def available() -> bool:
    try:
        load()
        return True
    except Exception:
        return False


# ---------------------------------------------------------------- wrappers

def _edges64(edges: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(edges).reshape(-1, 2), dtype=np.int64)


def degrees(edges: np.ndarray, n: int, out: Optional[np.ndarray] = None) -> np.ndarray:
    lib = load()
    e = _edges64(edges)
    if out is None:
        out = np.zeros(n, dtype=np.int64)
    lib.sheep_degrees(e, len(e), n, out)
    return out


def elim_order(deg: np.ndarray) -> np.ndarray:
    lib = load()
    d = np.ascontiguousarray(deg, dtype=np.int64)
    pos = np.empty(len(d), dtype=np.int64)
    lib.sheep_elim_order(d, len(d), pos)
    return pos


def build_elim_tree(edges: np.ndarray, pos: np.ndarray,
                    parent: Optional[np.ndarray] = None) -> np.ndarray:
    lib = load()
    e = _edges64(edges)
    p = np.ascontiguousarray(pos, dtype=np.int64)
    if parent is None:
        parent = np.full(len(p), -1, dtype=np.int64)
    else:
        parent = np.ascontiguousarray(parent, dtype=np.int64)
    lib.sheep_build_elim_tree(e, len(e), p, len(p), parent)
    return parent


def merge_trees(parent: np.ndarray, other: np.ndarray, pos: np.ndarray) -> np.ndarray:
    lib = load()
    parent = np.ascontiguousarray(parent, dtype=np.int64)
    lib.sheep_merge_trees(parent, np.ascontiguousarray(other, dtype=np.int64),
                          np.ascontiguousarray(pos, dtype=np.int64), len(parent))
    return parent


def tree_split(parent: np.ndarray, pos: np.ndarray, k: int,
               weights: Optional[np.ndarray] = None, alpha: float = 1.0) -> np.ndarray:
    lib = load()
    n = len(parent)
    # weights=None -> NULL: the C side treats it as unit weights without
    # either side materializing an O(n) ones array (8 GB at n = 2^30)
    w = None if weights is None \
        else np.ascontiguousarray(weights, dtype=np.float64)
    assign = np.empty(n, dtype=np.int32)
    lib.sheep_tree_split(
        np.ascontiguousarray(parent, dtype=np.int64),
        np.ascontiguousarray(pos, dtype=np.int64),
        w, n, k, alpha, assign)
    return assign


def score_chunk(edges: np.ndarray, assign: np.ndarray, n: int):
    lib = load()
    e = _edges64(edges)
    cut = ctypes.c_int64(0)
    total = ctypes.c_int64(0)
    lib.sheep_score_chunk(e, len(e), np.ascontiguousarray(assign, dtype=np.int32),
                          n, ctypes.byref(cut), ctypes.byref(total))
    return cut.value, total.value


def cut_pairs(edges: np.ndarray, assign: np.ndarray, n: int, k: int) -> np.ndarray:
    lib = load()
    e = _edges64(edges)
    out = np.empty(2 * len(e), dtype=np.int64)
    cnt = lib.sheep_cut_pairs(e, len(e), np.ascontiguousarray(assign, dtype=np.int32),
                              n, k, out)
    return out[:cnt]


def parse_text(data: bytes, max_edges: Optional[int] = None):
    """Parse complete 'u v' lines from a byte block -> (edges, bytes_consumed)."""
    lib = load()
    cap = max_edges if max_edges is not None else len(data) // 3 + 1
    out = np.empty((cap, 2), dtype=np.int64)
    consumed = ctypes.c_int64(0)
    cnt = lib.sheep_parse_text(data, len(data), out.reshape(-1), cap,
                               ctypes.byref(consumed))
    return out[:cnt].copy(), consumed.value


def rmat_hash_range(scale: int, start: int, count: int,
                    keys, keys2, thresholds) -> np.ndarray:
    """Native twin of generators._rmat_hash_uv over an edge-index range
    (bit-identical; asserted by tests/test_rmat_hash.py). ``keys``/
    ``keys2`` are the premixed per-level uint32 constants, ``thresholds``
    the (t_u, t_v0, t_v1) quadrant cutoffs."""
    lib = load()
    out = np.empty((count, 2), dtype=np.int64)
    lib.sheep_rmat_hash_range(
        scale, start, count,
        np.ascontiguousarray(keys, dtype=np.uint32),
        np.ascontiguousarray(keys2, dtype=np.uint32),
        int(thresholds[0]), int(thresholds[1]), int(thresholds[2]), out)
    return out


def sbm_hash_range(start: int, count: int, keys, keys2, t_out: int,
                   n_blocks: int, block_bits: int) -> np.ndarray:
    """Native twin of generators._sbm_hash_uv over an edge-index range
    (bit-identical; asserted by tests/test_sbm.py)."""
    lib = load()
    out = np.empty((count, 2), dtype=np.int64)
    lib.sheep_sbm_hash_range(
        start, count,
        np.ascontiguousarray(keys, dtype=np.uint32),
        np.ascontiguousarray(keys2, dtype=np.uint32),
        int(t_out), int(n_blocks), int(block_bits), out)
    return out


def has_sbm_hash() -> bool:
    try:
        return hasattr(load(), "sheep_sbm_hash_range")
    except Exception:
        return False
