// sheep_core — native single-socket CPU reference implementation.
//
// This is the rebuild of the reference's all-native C++ core
// (SURVEY.md §2 #11: the CPU reference path is the correctness and
// performance baseline the TPU backend is measured against). Exposed as a
// plain C ABI (loaded from Python via ctypes — no pybind11 in this
// environment); all buffers are caller-allocated numpy arrays.
//
// Algorithm notes
// ---------------
// The elimination-tree build uses an *incremental insertion* formulation
// rather than Liu's sorted vertex loop: maintaining the invariant that
// parent chains strictly increase in elimination position, inserting edge
// (u, v) with pos[u] < pos[v] walks up u's chain; if it meets a parent
// later than v, that parent edge is displaced and re-inserted as a new
// constraint. At fixpoint the forest is the elimination tree of every edge
// inserted so far, independent of insertion order — this is what makes the
// build streamable (chunks arrive in file order) and mergeable (inserting
// tree B's edges into tree A == T(A ∪ B)), per the SHEEP paper's
// partial-tree merge property (SURVEY.md §2 #6).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <numeric>
#include <queue>
#include <vector>

using i64 = int64_t;
using i32 = int32_t;

extern "C" {

// ---------------------------------------------------------------- degrees

// deg[v] += occurrences of v as an endpoint (self-loops count twice).
// Caller zero-initializes deg for the first chunk.
void sheep_degrees(const i64* edges, i64 m, i64 n, i64* deg) {
  for (i64 i = 0; i < 2 * m; ++i) {
    i64 v = edges[i];
    if (v >= 0 && v < n) deg[v]++;
  }
}

// ---------------------------------------------------------- elim ordering

// pos[v] = rank of v under (degree asc, id asc) — the global elimination
// order every backend shares (SURVEY.md §2 #3).
void sheep_elim_order(const i64* deg, i64 n, i64* pos) {
  std::vector<i64> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin(), order.end(), [&](i64 a, i64 b) {
    if (deg[a] != deg[b]) return deg[a] < deg[b];
    return a < b;
  });
  for (i64 r = 0; r < n; ++r) pos[order[r]] = r;
}

// ------------------------------------------------------- elim tree build

// Insert one connectivity constraint "u ~ v from time pos[v] on"
// (pos[u] < pos[v] required). Climbs are amortized short because the
// low-degree-first order keeps elimination trees shallow on real graphs.
static inline void insert_edge(i64 u, i64 v, const i64* pos, i64* parent) {
  while (true) {
    if (u == v) return;
    i64 p = parent[u];
    if (p < 0) {            // u was a root: v becomes its parent
      parent[u] = v;
      return;
    }
    if (p == v) return;     // constraint already present
    if (pos[p] < pos[v]) {  // u~p strictly earlier: constraint reduces to (p, v)
      u = p;
    } else {                // p later than v: v displaces p, re-insert (v, p)
      parent[u] = v;
      u = v;
      v = p;
    }
  }
}

// Build/extend the elimination forest from an edge chunk.
//
// Liu's sorted union-find pass over (carried tree edges ∪ chunk edges):
// counting-sort constraints by key = pos of the later endpoint, then for
// each in ascending key order link find(lo) under the key vertex. Path
// compression + the shallow low-degree-first trees make the DSU pass
// effectively linear; cost per chunk is O(V + C), so callers should use
// large chunks (the Python backend defaults to multi-million-edge chunks).
//
// The incremental insert_edge path above stays for small tree merges,
// where the O(V) sort setup would dominate.
void sheep_build_elim_tree(const i64* edges, i64 m, const i64* pos, i64 n,
                           i64* parent) {
  // order[p] = vertex at position p
  std::vector<i64> order(n);
  for (i64 v = 0; v < n; ++v) order[pos[v]] = v;

  // constraints: (key, lo). Tree edges contribute (pos[parent[v]], v).
  // Counting sort by key.
  std::vector<i64> counts(n + 1, 0);
  auto key_of = [&](i64 a, i64 b) { return std::max(pos[a], pos[b]); };
  for (i64 v = 0; v < n; ++v)
    if (parent[v] >= 0) counts[pos[parent[v]]]++;
  for (i64 i = 0; i < m; ++i) {
    i64 a = edges[2 * i], b = edges[2 * i + 1];
    if (a == b || a < 0 || b < 0 || a >= n || b >= n) continue;
    counts[key_of(a, b)]++;
  }
  i64 total = 0;
  for (i64 p = 0; p <= n; ++p) {
    i64 c = counts[p];
    counts[p] = total;
    total += c;
  }
  std::vector<i64> keys(total), los(total);
  auto place = [&](i64 lo, i64 k) {
    i64 at = counts[k]++;
    keys[at] = k;
    los[at] = lo;
  };
  for (i64 v = 0; v < n; ++v)
    if (parent[v] >= 0) place(v, pos[parent[v]]);
  for (i64 i = 0; i < m; ++i) {
    i64 a = edges[2 * i], b = edges[2 * i + 1];
    if (a == b || a < 0 || b < 0 || a >= n || b >= n) continue;
    if (pos[a] > pos[b]) std::swap(a, b);
    place(a, pos[b]);
  }

  // Liu's pass: fresh DSU; root of a merged component = its latest vertex.
  std::vector<i64> dsu(n);
  std::iota(dsu.begin(), dsu.end(), 0);
  auto find = [&](i64 x) {
    i64 root = x;
    while (dsu[root] != root) root = dsu[root];
    while (dsu[x] != root) {
      i64 nx = dsu[x];
      dsu[x] = root;
      x = nx;
    }
    return root;
  };
  for (i64 i = 0; i < total; ++i) {
    i64 hi = order[keys[i]];
    i64 r = find(los[i]);
    if (r != hi) {
      parent[r] = hi;
      dsu[r] = hi;
    }
  }
}

// Merge partial forest `other` into `parent` (associative, commutative):
// T(A ∪ B) by inserting B's tree edges into A.
void sheep_merge_trees(i64* parent, const i64* other, const i64* pos, i64 n) {
  for (i64 v = 0; v < n; ++v) {
    if (other[v] >= 0) insert_edge(v, other[v], pos, parent);
  }
}

// ------------------------------------------------------------ tree split

// Greedy bag-packing split — the same semantics as the Python reference
// (sheep_tpu/core/pure.py tree_split): walk vertices in ascending
// elimination order accumulating un-assigned subtree weight; at capacity,
// first-fit-pack child subtrees (descending) into <=cap bags handed to the
// least-loaded part. See that docstring for the invariants.
void sheep_tree_split(const i64* parent, const i64* pos, const double* w,
                      i64 n, i64 k, double alpha, i32* assign) {
  // w == nullptr means unit weights — callers need not materialize an
  // O(n) array of ones (8 GB at n = 2^30)
  auto W = [&](i64 v) { return w ? w[v] : 1.0; };

  // pos is a permutation of [0, n), so the position-order walk is its
  // inverse — O(n) fill instead of an O(n log n) comparator sort
  std::vector<i64> order(n);
  for (i64 v = 0; v < n; ++v) order[pos[v]] = v;

  double total = 0;
  for (i64 v = 0; v < n; ++v) total += W(v);
  double cap = std::max(alpha * total / double(k), 1.0);

  // children of v, position-ordered, in CSR layout: vertices are
  // processed in position order and every child precedes its parent,
  // so the original per-vertex push_back discovery order IS position
  // order — and "still uncut when the parent processes" is exactly
  // cut_part[c] < 0 at that moment. One flat array replaces the old
  // vector-of-vectors (whose 24 B/vertex of headers alone was 26 GB
  // at n = 2^30, the RMAT-30 class this split must handle).
  std::vector<i64> kid_off(n + 1, 0);
  for (i64 v = 0; v < n; ++v)
    if (parent[v] >= 0) ++kid_off[parent[v] + 1];
  for (i64 v = 0; v < n; ++v) kid_off[v + 1] += kid_off[v];
  std::vector<i64> kid_list(kid_off[n]);
  {
    std::vector<i64> fill(kid_off.begin(), kid_off.end() - 1);
    for (i64 idx = 0; idx < n; ++idx) {
      i64 v = order[idx];
      if (parent[v] >= 0) kid_list[fill[parent[v]]++] = v;
    }
  }

  std::vector<double> rem(n);
  for (i64 v = 0; v < n; ++v) rem[v] = W(v);
  std::vector<i32> cut_part(n, -1);

  // least-loaded part heap: (load, part), min by load then part id
  using Entry = std::pair<double, i64>;
  std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> loads;
  for (i64 p = 0; p < k; ++p) loads.push({0.0, p});

  auto flush = [&](const std::vector<i64>& bag, i64 extra, double bagw) {
    Entry e = loads.top();
    loads.pop();
    for (i64 x : bag) cut_part[x] = (i32)e.second;
    if (extra >= 0) cut_part[extra] = (i32)e.second;
    loads.push({e.first + bagw, e.second});
  };

  std::vector<i64> bag;
  std::vector<i64> kids;  // reused scratch: the uncut children of v
  for (i64 idx = 0; idx < n; ++idx) {
    i64 v = order[idx];
    kids.clear();
    for (i64 j = kid_off[v]; j < kid_off[v + 1]; ++j) {
      i64 c = kid_list[j];
      if (cut_part[c] < 0) kids.push_back(c);
    }
    double tot = W(v);
    for (i64 c : kids) tot += rem[c];
    bool is_root = parent[v] < 0;
    if (tot < cap && !is_root) {
      rem[v] = tot;
      continue;
    }
    // stable: equal-rem ties keep discovery order, matching the Python
    // reference's list.sort so native/pure assignments are bit-identical
    std::stable_sort(kids.begin(), kids.end(),
                     [&](i64 a, i64 b) { return rem[a] > rem[b]; });
    bag.clear();
    double bagw = 0.0;
    for (i64 c : kids) {
      if (!bag.empty() && bagw + rem[c] > cap) {
        flush(bag, -1, bagw);
        bag.clear();
        bagw = 0.0;
      }
      bag.push_back(c);
      bagw += rem[c];
    }
    if (is_root || bagw + W(v) >= cap) {
      flush(bag, v, bagw + W(v));
    } else {
      rem[v] = bagw + W(v);
    }
  }

  // top-down labeling: nearest cut ancestor owns the vertex
  for (i64 idx = n - 1; idx >= 0; --idx) {
    i64 v = order[idx];
    assign[v] = cut_part[v] >= 0 ? cut_part[v]
                                 : (parent[v] >= 0 ? assign[parent[v]] : 0);
  }
}

// --------------------------------------------------------------- scoring

// One pass over a chunk: cut/total counters accumulate (caller zeroes
// before the first chunk), per-part loads accumulate into loads[k].
void sheep_score_chunk(const i64* edges, i64 m, const i32* assign, i64 n,
                       i64* cut, i64* total) {
  i64 c = 0, t = 0;
  for (i64 i = 0; i < m; ++i) {
    i64 a = edges[2 * i], b = edges[2 * i + 1];
    if (a == b || a < 0 || b < 0 || a >= n || b >= n) continue;
    t++;
    if (assign[a] != assign[b]) c++;
  }
  *cut += c;
  *total += t;
}

// Write encoded (vertex * k + foreign_part) pairs for cut edges in the
// chunk into out (caller provides 2*m capacity); returns count written.
// Comm volume = unique count across all chunks (done host-side).
i64 sheep_cut_pairs(const i64* edges, i64 m, const i32* assign, i64 n, i64 k,
                    i64* out) {
  i64 w = 0;
  for (i64 i = 0; i < m; ++i) {
    i64 a = edges[2 * i], b = edges[2 * i + 1];
    if (a == b || a < 0 || b < 0 || a >= n || b >= n) continue;
    i32 pa = assign[a], pb = assign[b];
    if (pa != pb) {
      out[w++] = a * k + pb;
      out[w++] = b * k + pa;
    }
  }
  return w;
}

// ----------------------------------------------------- text edge parsing

// Fast SNAP-style text parser: consumes complete "u v" lines from buf,
// skipping '#'/'%' comments and blanks. Returns edges written; *consumed =
// bytes of buf fully processed (caller re-feeds the tail + next block).
i64 sheep_parse_text(const char* buf, i64 len, i64* out, i64 max_edges,
                     i64* consumed) {
  i64 w = 0;
  i64 i = 0;
  *consumed = 0;
  while (i < len && w < max_edges) {
    i64 line_start = i;
    // find end of line
    i64 j = i;
    while (j < len && buf[j] != '\n') j++;
    if (j == len) break;  // incomplete line: leave for next block
    // parse the line
    i64 p = i;
    while (p < j && (buf[p] == ' ' || buf[p] == '\t' || buf[p] == '\r')) p++;
    if (p < j && buf[p] != '#' && buf[p] != '%') {
      i64 u = 0, v = 0;
      bool ok = false;
      while (p < j && buf[p] >= '0' && buf[p] <= '9') {
        u = u * 10 + (buf[p] - '0');
        p++;
        ok = true;
      }
      while (p < j && (buf[p] == ' ' || buf[p] == '\t')) p++;
      bool ok2 = false;
      while (p < j && buf[p] >= '0' && buf[p] <= '9') {
        v = v * 10 + (buf[p] - '0');
        p++;
        ok2 = true;
      }
      if (ok && ok2) {
        out[2 * w] = u;
        out[2 * w + 1] = v;
        w++;
      }
    }
    i = j + 1;
    *consumed = i;
    (void)line_start;
  }
  return w;
}

// ---------------------------------------------------- synthetic generator

// Counter-based R-MAT, bit-identical to io/generators.py _rmat_hash_uv
// (same murmur-style uint32 arithmetic): one hash per (edge index,
// level); its 16-bit halves pick the recursion quadrant against integer
// thresholds. ``keys``/``keys2`` are the per-level premixed constants
// (keys2[b] = fmix32(keys[b] ^ 0x7FEB352D), computed by the caller so
// the constants cannot drift between the three implementations). The
// native path exists because host generation was the soak bottleneck:
// numpy hashes ~0.1-0.4 M edges/s/core at scale 27, this loop tens of M.
void sheep_rmat_hash_range(i64 scale, i64 start, i64 count,
                           const uint32_t* keys, const uint32_t* keys2,
                           uint32_t t_u, uint32_t t_v0, uint32_t t_v1,
                           i64* out) {
  for (i64 i = 0; i < count; ++i) {
    uint64_t e = (uint64_t)(start + i);
    uint32_t elo = (uint32_t)e, ehi = (uint32_t)(e >> 32);
    uint32_t u = 0, v = 0;
    for (i64 b = 0; b < scale; ++b) {
      uint32_t h = elo ^ keys[b];
      h ^= h >> 16;
      h *= 0x85EBCA6Bu;
      h ^= ehi ^ keys2[b];
      h ^= h >> 13;
      h *= 0xC2B2AE35u;
      h ^= h >> 16;
      uint32_t ubit = (h >> 16) < t_u;
      uint32_t vbit = (h & 0xFFFFu) < (ubit ? t_v1 : t_v0);
      u |= ubit << b;
      v |= vbit << b;
    }
    out[2 * i] = (i64)u;
    out[2 * i + 1] = (i64)v;
  }
}

// Counter-based planted partition (SBM), host twin of
// io/generators.py _sbm_hash_uv — same fmix32-with-fold per field, five
// per-field keys (decide, bu, bv, uoff, voff). Bit-identical to the
// numpy/jnp bodies; the native loop exists because at-scale SBM quality
// runs re-stream the graph once per refine round (tools/sbm_quality.py)
// and host numpy hashing would dominate the measurement.
void sheep_sbm_hash_range(i64 start, i64 count, const uint32_t* keys,
                          const uint32_t* keys2, uint32_t t_out,
                          i64 n_blocks, i64 block_bits, i64* out) {
  uint32_t nb1 = (uint32_t)(n_blocks - 1);
  uint32_t off_mask = (uint32_t)((1u << block_bits) - 1u);
  for (i64 i = 0; i < count; ++i) {
    uint64_t e = (uint64_t)(start + i);
    uint32_t elo = (uint32_t)e, ehi = (uint32_t)(e >> 32);
    uint32_t f[5];
    for (int j = 0; j < 5; ++j) {
      uint32_t h = elo ^ keys[j];
      h ^= h >> 16;
      h *= 0x85EBCA6Bu;
      h ^= ehi ^ keys2[j];
      h ^= h >> 13;
      h *= 0xC2B2AE35u;
      h ^= h >> 16;
      f[j] = h;
    }
    uint32_t bu = f[1] & nb1;
    uint32_t bvr = f[2] % nb1;  // [0, n_blocks-1)
    uint32_t bv = bvr + (bvr >= bu ? 1u : 0u);
    uint32_t b2 = (f[0] < t_out) ? bv : bu;
    out[2 * i] = (i64)(((uint64_t)bu << block_bits) | (f[3] & off_mask));
    out[2 * i + 1] = (i64)(((uint64_t)b2 << block_bits) | (f[4] & off_mask));
  }
}

// ------------------------------------------------------------- utilities

i64 sheep_core_abi_version() { return 1; }

}  // extern "C"
