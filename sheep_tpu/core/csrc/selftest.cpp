// Sanitizer selftest for the native core (SURVEY.md §5 "Race detection /
// sanitizers"): exercises every exported function on synthetic graphs with
// invariant checks, built with -fsanitize=address,undefined by
// `make sanitize` and run by tests/test_sanitize.py. A standalone binary
// (rather than loading a sanitized .so into Python) so the ASan runtime
// needs no LD_PRELOAD gymnastics.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

using i64 = int64_t;
using i32 = int32_t;

extern "C" {
void sheep_degrees(const i64*, i64, i64, i64*);
void sheep_elim_order(const i64*, i64, i64*);
void sheep_build_elim_tree(const i64*, i64, const i64*, i64, i64*);
void sheep_merge_trees(i64*, const i64*, const i64*, i64);
void sheep_tree_split(const i64*, const i64*, const double*, i64, i64, double,
                      i32*);
void sheep_score_chunk(const i64*, i64, const i32*, i64, i64*, i64*);
i64 sheep_cut_pairs(const i64*, i64, const i32*, i64, i64, i64*);
i64 sheep_parse_text(const char*, i64, i64*, i64, i64*);
void sheep_rmat_hash_range(i64, i64, i64, const uint32_t*, const uint32_t*,
                           uint32_t, uint32_t, uint32_t, i64*);
void sheep_sbm_hash_range(i64, i64, const uint32_t*, const uint32_t*,
                          uint32_t, i64, i64, i64*);
i64 sheep_core_abi_version();
}

static uint64_t rng_state = 0x9e3779b97f4a7c15ull;
static uint64_t rng() {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return rng_state;
}

#define CHECK(cond, msg)                              \
  do {                                                \
    if (!(cond)) {                                    \
      std::fprintf(stderr, "FAIL: %s\n", msg);        \
      std::exit(1);                                   \
    }                                                 \
  } while (0)

int main() {
  CHECK(sheep_core_abi_version() == 1, "abi version");

  const i64 n = 700, m = 4000, k = 7;
  std::vector<i64> edges(2 * m);
  for (i64 i = 0; i < m; ++i) {
    edges[2 * i] = (i64)(rng() % n);
    edges[2 * i + 1] = (i64)(rng() % n);
  }
  // a few malformed rows exercise the bounds checks
  edges[0] = -3;
  edges[3] = n + 17;
  edges[10] = edges[11];  // self loop

  std::vector<i64> deg(n, 0);
  sheep_degrees(edges.data(), m, n, deg.data());

  std::vector<i64> pos(n);
  sheep_elim_order(deg.data(), n, pos.data());
  std::vector<char> seen(n, 0);
  for (i64 v = 0; v < n; ++v) {
    CHECK(pos[v] >= 0 && pos[v] < n, "pos in range");
    CHECK(!seen[pos[v]], "pos is a permutation");
    seen[pos[v]] = 1;
  }

  // one-shot build vs chunked build + merge must agree (associativity)
  std::vector<i64> parent(n, -1);
  sheep_build_elim_tree(edges.data(), m, pos.data(), n, parent.data());
  for (i64 v = 0; v < n; ++v)
    if (parent[v] >= 0)
      CHECK(pos[parent[v]] > pos[v], "parent later in elimination order");

  std::vector<i64> pa(n, -1), pb(n, -1);
  const i64 half = m / 2;
  sheep_build_elim_tree(edges.data(), half, pos.data(), n, pa.data());
  sheep_build_elim_tree(edges.data() + 2 * half, m - half, pos.data(), n,
                        pb.data());
  sheep_merge_trees(pa.data(), pb.data(), pos.data(), n);
  CHECK(std::memcmp(pa.data(), parent.data(), n * sizeof(i64)) == 0,
        "chunked+merged tree == one-shot tree");

  std::vector<double> w(n, 1.0);
  std::vector<i32> assign(n, -1);
  sheep_tree_split(parent.data(), pos.data(), w.data(), n, k, 1.0,
                   assign.data());
  for (i64 v = 0; v < n; ++v)
    CHECK(assign[v] >= 0 && assign[v] < k, "assignment in range");
  // w == nullptr is the unit-weight fast path; must match explicit ones
  std::vector<i32> assign0(n, -1);
  sheep_tree_split(parent.data(), pos.data(), nullptr, n, k, 1.0,
                   assign0.data());
  CHECK(std::memcmp(assign0.data(), assign.data(), n * sizeof(i32)) == 0,
        "null weights == explicit unit weights");

  i64 cut = 0, total = 0;
  sheep_score_chunk(edges.data(), m, assign.data(), n, &cut, &total);
  CHECK(total <= m && cut <= total, "score counters sane");

  std::vector<i64> pairs(2 * m);
  i64 npairs = sheep_cut_pairs(edges.data(), m, assign.data(), n, k,
                               pairs.data());
  CHECK(npairs == 2 * cut, "two cut pairs per cut edge");

  const char* text = "# comment\n1 2\n\n3\t4\n 9 9 \nbogus line\n5 6";
  std::vector<i64> out(64);
  i64 consumed = 0;
  i64 ne = sheep_parse_text(text, (i64)std::strlen(text), out.data(), 32,
                            &consumed);
  CHECK(ne == 3, "parsed complete lines only");
  CHECK(out[0] == 1 && out[1] == 2 && out[4] == 9, "parsed values");

  // counter-hash generators: sanitized pass over a 64-bit-boundary range
  // (start chosen so elo wraps mid-range), ids must stay in range
  {
    std::vector<uint32_t> hk = {1u, 2u, 3u, 4u, 5u};
    std::vector<uint32_t> hk2 = {9u, 8u, 7u, 6u, 5u};
    i64 cnt = 256;
    std::vector<i64> he(2 * cnt);
    sheep_rmat_hash_range(5, (i64)0xFFFFFF80LL, cnt, hk.data(), hk2.data(),
                          32768u, 32768u, 32768u, he.data());
    for (i64 i = 0; i < 2 * cnt; ++i)
      CHECK(he[i] >= 0 && he[i] < 32, "rmat hash ids in range");
    sheep_sbm_hash_range((i64)0xFFFFFF80LL, cnt, hk.data(), hk2.data(),
                         214748365u /* p_out=0.05 */, 8, 7, he.data());
    for (i64 i = 0; i < 2 * cnt; ++i)
      CHECK(he[i] >= 0 && he[i] < 1024, "sbm hash ids in range");
  }

  std::puts("selftest OK");
  return 0;
}
