"""Pure numpy reference implementation of the SHEEP pipeline.

This is the executable spec: the C++ CPU core (SURVEY.md §2 #11) and the
JAX TPU backend are both equivalence-tested against it. Algorithm per the
SHEEP paper (PVLDB 8(12) 2015) as reconstructed in SURVEY.md §3:

degree sort -> union-find elimination-tree build (Liu's algorithm) ->
associative partial-tree merge -> greedy tree split -> edge-cut scoring.

Key identity this whole framework is built on (makes the algorithm
map-reduce-able and hence TPU-shardable): with a fixed global elimination
order, ``T(G1 ∪ G2) = T(T(G1) ∪ T(G2))`` — the elimination tree of a union
of edge sets equals the elimination tree of the union of the partial trees'
edges. Liu's vertex loop is equivalently Kruskal's union-find over edges
keyed by the *later* endpoint's position, with the later endpoint becoming
the merged component's root; ``parent[r] = v`` records each link.
"""

from __future__ import annotations

import heapq
from typing import Optional, Tuple

import numpy as np

from sheep_tpu.types import ElimTree, PartitionResult


# --------------------------------------------------------------------------
# degrees + elimination order (SURVEY.md §2 #3)
# --------------------------------------------------------------------------

def degrees(edges: np.ndarray, n: int) -> np.ndarray:
    """Endpoint-count degrees (self-loops count twice, multi-edges count)."""
    return np.bincount(np.asarray(edges).ravel(), minlength=n).astype(np.int64)


def elimination_order(deg: np.ndarray, dtype=np.int64) -> np.ndarray:
    """pos[v] = rank of v ordered by (degree asc, id asc).

    Ties broken by id so the order is a pure function of the degree table —
    every shard/backend derives the identical global order, which is what
    makes partial trees mergeable.

    A STABLE argsort ties by original index by definition, so it equals
    the old ``lexsort((arange(n), deg))`` exactly while allocating one
    temp fewer — at the RMAT-30 class (n = 2^30) the arange key alone
    was 8 GB. ``dtype`` sizes the returned ranks (int32 suffices for
    every TPU-backend graph; the default stays int64 for the oracle).
    """
    n = len(deg)
    order = np.argsort(deg, kind="stable")  # vertex ids in elimination order
    pos = np.empty(n, dtype=dtype)
    pos[order] = np.arange(n, dtype=dtype)
    return pos


# --------------------------------------------------------------------------
# elimination-tree build (SURVEY.md §2 #4, #5) — Liu's algorithm
# --------------------------------------------------------------------------

def build_elim_tree(edges: np.ndarray, pos: np.ndarray, parent: Optional[np.ndarray] = None) -> ElimTree:
    """Build (or extend) an elimination forest from an edge multiset.

    Kruskal formulation: process edges in ascending key = pos of the later
    endpoint; link the earlier endpoint's current component root under the
    later endpoint. Union-find with path compression; the *tree* parent
    array records the link structure and is returned.

    Passing a previous ``parent`` continues the stream: the prior forest's
    edges are prepended, which by the merge identity gives the tree of the
    union of everything seen so far.
    """
    n = len(pos)
    e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if parent is not None:
        prev = np.nonzero(parent >= 0)[0]
        e = np.concatenate([np.stack([prev, parent[prev]], axis=1), e], axis=0)

    # orient each edge (lo, hi) by position; drop self-loops
    swap = pos[e[:, 0]] > pos[e[:, 1]]
    lo = np.where(swap, e[:, 1], e[:, 0])
    hi = np.where(swap, e[:, 0], e[:, 1])
    keep = lo != hi
    lo, hi = lo[keep], hi[keep]
    order = np.argsort(pos[hi], kind="stable")
    lo, hi = lo[order], hi[order]

    tree_parent = np.full(n, -1, dtype=np.int64)
    dsu = np.arange(n, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while dsu[root] != root:
            root = dsu[root]
        while dsu[x] != root:  # path compression
            dsu[x], x = root, dsu[x]
        return root

    for u, v in zip(lo.tolist(), hi.tolist()):
        # Processing edges in ascending pos[hi]: v cannot yet have been
        # linked (links only happen at strictly later keys), so v is its own
        # component root; u ~ v already iff find(u) == v.
        r = find(u)
        if r != v:
            tree_parent[r] = v
            dsu[r] = v
    return ElimTree(parent=tree_parent, pos=pos, n=n)


def merge_trees(a: ElimTree, b: ElimTree) -> ElimTree:
    """Associative, commutative merge of partial forests (SURVEY.md §2 #6):
    T(A ∪ B) via rebuilding over the union of the trees' O(V) edge sets."""
    assert a.n == b.n and np.array_equal(a.pos, b.pos)
    return build_elim_tree(np.concatenate([a.edges(), b.edges()]), a.pos)


# --------------------------------------------------------------------------
# tree split (SURVEY.md §2 #7)
# --------------------------------------------------------------------------

def tree_split(
    tree: ElimTree,
    k: int,
    weights: Optional[np.ndarray] = None,
    alpha: float = 1.0,
) -> np.ndarray:
    """Greedy k-way split of the elimination forest.

    Bottom-up bag packing: walk vertices in ascending elimination order
    (children strictly precede parents since pos[parent] > pos[child]),
    accumulating each vertex's un-assigned subtree weight ``rem``. When a
    vertex's accumulation reaches the bag capacity (``alpha * total/k``),
    its un-cut child subtrees are first-fit-packed (descending) into bags of
    at most capacity; each full bag goes to the currently least-loaded part
    (LPT-style). Sibling subtrees in one bag are connected only through the
    (uncut) parent, so bagging costs the same tree edges a plain subtree cut
    would. Residue below capacity propagates upward; root residue joins the
    least-loaded part. Invariant: every propagated ``rem`` < capacity, so no
    bag except a single heavy vertex can exceed capacity. O(V log V).
    """
    n, parent, pos = tree.n, tree.parent, tree.pos
    if weights is None:
        weights = np.ones(n, dtype=np.int64)
    w = weights.astype(np.float64)
    total = float(w.sum())
    cap = max(alpha * total / k, 1.0)

    order = np.argsort(pos, kind="stable")  # ascending elimination order
    rem = w.copy()  # un-assigned weight accumulated at each vertex
    uncut_kids: list = [[] for _ in range(n)]  # children whose rem propagated
    cut_part = np.full(n, -1, dtype=np.int32)
    loads = [(0.0, p) for p in range(k)]
    heapq.heapify(loads)

    def flush(bag_vertices, bag_weight):
        load, p = heapq.heappop(loads)
        for x in bag_vertices:
            cut_part[x] = p
        heapq.heappush(loads, (load + bag_weight, p))

    for v in order.tolist():
        kids = uncut_kids[v]
        tot = w[v] + sum(rem[c] for c in kids)
        is_root = parent[v] < 0
        if tot < cap and not is_root:
            rem[v] = tot
            uncut_kids[int(parent[v])].append(v)
            continue
        # pack child subtrees (each rem < cap by invariant) into bags
        kids.sort(key=lambda c: -rem[c])
        bag: list = []
        bagw = 0.0
        for c in kids:
            if bag and bagw + rem[c] > cap:
                flush(bag, bagw)
                bag, bagw = [], 0.0
            bag.append(c)
            bagw += rem[c]
        if is_root or bagw + w[v] >= cap:
            # cut v itself together with the last bag
            flush(bag + [v], bagw + w[v])
        else:
            # last bag stays attached to v and propagates upward
            rem[v] = bagw + w[v]
            uncut_kids[int(parent[v])].append(v)

    # top-down labeling: nearest cut ancestor owns the vertex
    assignment = np.full(n, -1, dtype=np.int32)
    for v in order[::-1].tolist():
        if cut_part[v] >= 0:
            assignment[v] = cut_part[v]
        else:
            assignment[v] = assignment[parent[v]]
    return assignment


# --------------------------------------------------------------------------
# scoring (SURVEY.md §2 #8, §3.4)
# --------------------------------------------------------------------------

def cut_pairs(edges: np.ndarray, assignment: np.ndarray, k: int) -> np.ndarray:
    """Encoded (vertex * k + foreign_part) pairs for every cut edge.

    Communication volume = number of *distinct* such pairs; streaming
    callers concatenate per-chunk pair arrays and unique at the end.
    """
    e = np.asarray(edges).reshape(-1, 2)
    pu = assignment[e[:, 0]]
    pv = assignment[e[:, 1]]
    m = (pu != pv) & (e[:, 0] != e[:, 1])
    return np.concatenate([e[m, 0] * np.int64(k) + pv[m], e[m, 1] * np.int64(k) + pu[m]])


def part_balance(assignment: np.ndarray, k: int, weights: Optional[np.ndarray] = None) -> float:
    """max part load / ideal load (1.0 = perfect)."""
    if weights is None:
        weights = np.ones(len(assignment), dtype=np.int64)
    loads = np.bincount(assignment, weights=weights, minlength=k)
    return float(loads.max() / (weights.sum() / k)) if weights.sum() else 1.0


def edge_cut_score(
    edges: np.ndarray,
    assignment: np.ndarray,
    k: int,
    weights: Optional[np.ndarray] = None,
    comm_volume: bool = True,
) -> Tuple[int, int, float, Optional[int]]:
    """One streaming pass: (edge_cut, total_edges, balance, comm_volume)."""
    e = np.asarray(edges).reshape(-1, 2)
    nonloop = e[:, 0] != e[:, 1]
    pu = assignment[e[:, 0]]
    pv = assignment[e[:, 1]]
    cut = int(np.count_nonzero((pu != pv) & nonloop))
    total = int(nonloop.sum())
    balance = part_balance(assignment, k, weights)
    cv = int(len(np.unique(cut_pairs(e, assignment, k)))) if comm_volume else None
    return cut, total, balance, cv


# --------------------------------------------------------------------------
# full pipeline (reference semantics for backends)
# --------------------------------------------------------------------------

def partition_arrays(
    edges: np.ndarray, k: int, n: Optional[int] = None, weights: str = "unit"
) -> PartitionResult:
    e = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if n is None:
        n = int(e.max()) + 1 if len(e) else 0
    deg = degrees(e, n)
    pos = elimination_order(deg)
    tree = build_elim_tree(e, pos)
    w = deg if weights == "degree" else None
    assignment = tree_split(tree, k, w)
    cut, total, balance, cv = edge_cut_score(e, assignment, k, w)
    return PartitionResult(
        assignment=assignment,
        k=k,
        edge_cut=cut,
        total_edges=total,
        cut_ratio=cut / max(total, 1),
        balance=balance,
        comm_volume=cv,
        backend="pure",
    )
