"""``python -m sheep_tpu`` == ``python -m sheep_tpu.cli``."""

from sheep_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
