"""Cross-replica metric federation (ISSUE 18 tentpole, layer 2).

A sheep fleet is N independent sheepd daemons, each answering its own
``metrics`` scrape. Dashboards and the SLO gate need ONE view, and the
merge must be exact, not impressionistic:

- **counters** (``# TYPE ... counter``, plus histogram ``_sum`` /
  ``_count`` components) SUM across replicas per label set — a fleet
  total is the sum of replica totals, full stop;
- **gauges** do NOT sum (adding two queue depths fabricates a queue
  nobody has); every gauge sample instead gains a ``replica`` label so
  per-replica levels stay distinguishable in one document;
- **histograms** merge bucket-by-bucket: cumulative ``le`` counts add
  when every replica reporting the series uses the SAME boundaries —
  the registry pins its bucket sets precisely so this holds
  (``metrics.DEFAULT_LATENCY_BUCKETS`` et al.). A boundary mismatch
  raises :class:`FederationError` LOUDLY; silently interpolating
  mismatched buckets would skew every fleet quantile downstream.

Unreachable or empty replicas DEGRADE rather than fail: the merge
covers the replicas that answered and the record carries a warning per
missing one (also rendered as ``# federation-warning`` comments and a
``sheep_federated_up{replica=...}`` gauge, so a scrape of the
federated document shows who was absent).

Scrape sources: a unix socket path (the sheepd wire ``metrics`` verb),
an ``http(s)://`` URL (the ``--metrics-port`` listener), or a plain
file of saved exposition text — mix freely. Stdlib only, like the rest
of the metrics plane.

CLI (console script ``sheep-fleet-metrics``)::

    sheep-fleet-metrics /tmp/a.sock /tmp/b.sock          # merged text
    sheep-fleet-metrics --endpoints A,B \\
        --quantile sheepd_request_latency_seconds:0.99   # fleet p99

``sheeptop --endpoints A,B`` and ``tools/slo_check.py`` consume the
same :func:`federate` record.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import stat
import sys
from typing import Dict, List, Optional, Tuple

from sheep_tpu.obs.metrics import (_escape_label, _fmt,
                                   histogram_series_quantile,
                                   parse_prometheus)


class FederationError(ValueError):
    """A merge that cannot be exact — histogram bucket boundaries
    disagree across replicas. Raised loudly on purpose: every quantile
    computed over a silently-approximated merge would be skew."""


_TYPE_RE = re.compile(
    r"^#\s*TYPE\s+([a-zA-Z_:][a-zA-Z0-9_:]*)\s+(\S+)\s*$", re.M)


def parse_types(text: str) -> Dict[str, str]:
    """``{name: kind}`` from the exposition ``# TYPE`` comments —
    parse_prometheus drops comments, but federation needs the kind to
    pick the merge rule."""
    return {m.group(1): m.group(2) for m in _TYPE_RE.finditer(text)}


def _le_key(le: str) -> float:
    return float(str(le).replace("+Inf", "inf"))


def _labels_key(labels: dict, drop: Tuple[str, ...] = ()) -> tuple:
    return tuple(sorted((k, v) for k, v in labels.items()
                        if k not in drop))


def scrape_endpoint(endpoint: str, timeout_s: float = 10.0) -> str:
    """Fetch one replica's exposition text. ``endpoint`` is a unix
    socket path (wire ``metrics`` verb), an http(s) URL, or a plain
    file of saved text. Raises on failure — the caller decides whether
    that degrades or aborts."""
    if endpoint.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(endpoint, timeout=timeout_s) as r:
            return r.read().decode("utf-8", "replace")
    try:
        mode = os.stat(endpoint).st_mode
    except OSError:
        mode = None
    if mode is not None and stat.S_ISREG(mode):
        with open(endpoint) as f:
            return f.read()
    from sheep_tpu.server.client import SheepClient

    with SheepClient(endpoint, timeout_s=timeout_s) as c:
        return c.metrics()


def federate(scrapes: List[Tuple[str, Optional[str]]]) -> dict:
    """Merge replica scrapes into one record::

        {"samples": {name: [(labels, value)]},   # parse_prometheus shape
         "kinds":   {name: "counter"|"gauge"|"histogram"},
         "replicas": [every replica name given],
         "answered": [replicas whose scrape merged],
         "warnings": ["replica B: ...", ...]}

    ``scrapes`` is ``[(replica_name, exposition_text_or_None)]`` —
    pass None (or empty text) for a replica whose fetch failed; it
    degrades to a warning instead of poisoning the merge. ``samples``
    keeps the parse_prometheus shape so
    :func:`~sheep_tpu.obs.metrics.histogram_series_quantile` runs on a
    federated ``<name>_bucket`` list unchanged."""
    parsed: List[Tuple[str, dict]] = []
    warnings: List[str] = []
    kinds: Dict[str, str] = {}
    for replica, text in scrapes:
        if not text or not text.strip():
            warnings.append(f"replica {replica}: no scrape "
                            f"(unreachable or empty) — fleet view "
                            f"covers the others only")
            continue
        p = parse_prometheus(text)
        if not p:
            warnings.append(f"replica {replica}: scrape held no "
                            f"samples — fleet view covers the others "
                            f"only")
            continue
        for name, kind in parse_types(text).items():
            kinds.setdefault(name, kind)
        parsed.append((replica, p))

    # histogram families: the base name of every *_bucket series with
    # an le label (TYPE comments alone cannot be trusted — a saved
    # scrape may have been stripped of comments)
    hist_bases = set()
    for _, p in parsed:
        for name, samples in p.items():
            if name.endswith("_bucket") \
                    and any("le" in ls for ls, _ in samples):
                hist_bases.add(name[:-len("_bucket")])
    for base in hist_bases:
        kinds[base] = "histogram"

    def kind_of(name: str) -> str:
        for base in hist_bases:
            if name in (base + "_bucket", base + "_sum",
                        base + "_count"):
                return "histogram-part"
        k = kinds.get(name)
        if k in ("counter", "gauge"):
            return k
        return "counter" if name.endswith("_total") else "gauge"

    merged: Dict[str, List[Tuple[dict, float]]] = {}

    # -- histograms: exact bucket-wise merge ---------------------------
    for base in sorted(hist_bases):
        bname = base + "_bucket"
        per_series: Dict[tuple, dict] = {}
        for replica, p in parsed:
            for labels, value in p.get(bname, []):
                le = labels.get("le")
                if le is None:
                    continue
                key = _labels_key(labels, drop=("le",))
                per_series.setdefault(key, {}) \
                    .setdefault(replica, {})[str(le)] = value
        out_buckets: List[Tuple[dict, float]] = []
        for key, by_rep in sorted(per_series.items()):
            boundary_sets = {
                rep: tuple(sorted(d, key=_le_key))
                for rep, d in by_rep.items()}
            distinct = sorted(set(boundary_sets.values()))
            if len(distinct) > 1:
                detail = "; ".join(
                    f"{rep}: le={list(bs)}"
                    for rep, bs in sorted(boundary_sets.items()))
                raise FederationError(
                    f"histogram {base}{dict(key)} has MISMATCHED "
                    f"bucket boundaries across replicas — refusing "
                    f"to merge (quantiles over interpolated buckets "
                    f"are silent skew). {detail}")
            for le in distinct[0]:
                total = sum(d[le] for d in by_rep.values())
                out_buckets.append((dict(key, le=le), total))
        if out_buckets:
            merged[bname] = out_buckets
        for part in ("_sum", "_count"):
            acc: Dict[tuple, float] = {}
            for replica, p in parsed:
                for labels, value in p.get(base + part, []):
                    key = _labels_key(labels)
                    acc[key] = acc.get(key, 0.0) + value
            if acc:
                merged[base + part] = [(dict(k), v)
                                       for k, v in sorted(acc.items())]

    # -- counters sum; gauges gain a replica label ---------------------
    for replica, p in parsed:
        for name, samples in p.items():
            k = kind_of(name)
            if k == "histogram-part":
                continue
            if k == "counter":
                rows = merged.setdefault(name, [])
                for labels, value in samples:
                    key = _labels_key(labels)
                    for i, (ls, v) in enumerate(rows):
                        if _labels_key(ls) == key:
                            rows[i] = (ls, v + value)
                            break
                    else:
                        rows.append((dict(labels), value))
            else:
                rows = merged.setdefault(name, [])
                for labels, value in samples:
                    rows.append((dict(labels, replica=replica), value))

    # who answered, as a scrapeable series on the merged document
    answered = [r for r, _ in parsed]
    kinds["sheep_federated_up"] = "gauge"
    merged["sheep_federated_up"] = [
        ({"replica": r}, 1.0 if r in answered else 0.0)
        for r, _t in scrapes]

    return {"samples": merged, "kinds": kinds,
            "replicas": [r for r, _t in scrapes],
            "answered": answered, "warnings": warnings}


def _fmt_value(v: float) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f) or f != int(f) or abs(f) >= 1e15:
        return _fmt(f)
    return str(int(f))


def render_federated(fed: dict) -> str:
    """One exposition document from a :func:`federate` record:
    warnings as comments, families sorted by name (histogram parts
    grouped under their base), buckets ordered by ``le``."""
    out: List[str] = []
    for w in fed["warnings"]:
        out.append(f"# federation-warning: {w}")
    samples = fed["samples"]
    kinds = fed["kinds"]
    bases = {n[:-len("_bucket")] for n in samples
             if n.endswith("_bucket")
             and kinds.get(n[:-len("_bucket")]) == "histogram"}
    done = set()
    for name in sorted(samples):
        base = next((b for b in bases
                     if name in (b + "_bucket", b + "_sum",
                                 b + "_count")), None)
        if base is not None:
            if base in done:
                continue
            done.add(base)
            out.append(f"# TYPE {base} histogram")
            for labels, value in sorted(
                    samples.get(base + "_bucket", []),
                    key=lambda s: (_labels_key(s[0], drop=("le",)),
                                   _le_key(s[0].get("le", "inf")))):
                out.append(_sample_line(base + "_bucket", labels,
                                        value))
            for part in ("_sum", "_count"):
                for labels, value in samples.get(base + part, []):
                    out.append(_sample_line(base + part, labels, value))
            continue
        kind = kinds.get(name) or \
            ("counter" if name.endswith("_total") else "gauge")
        out.append(f"# TYPE {name} {kind}")
        for labels, value in sorted(
                samples[name], key=lambda s: _labels_key(s[0])):
            out.append(_sample_line(name, labels, value))
    return "\n".join(out) + "\n"


def _sample_line(name: str, labels: dict, value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape_label(v)}"'
                         for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def fleet_quantile(fed: dict, name: str, q: float,
                   match: Optional[dict] = None) -> Optional[float]:
    """A quantile over the FEDERATED histogram — computed from the
    merged cumulative buckets, i.e. over the union of every replica's
    observations (exact to bucket resolution)."""
    return histogram_series_quantile(
        fed["samples"].get(name + "_bucket", []), q, match)


def scrape_fleet(endpoints: List[str],
                 timeout_s: float = 10.0) -> List[Tuple[str, Optional[str]]]:
    """Fetch every endpoint, mapping per-replica failures to None (the
    degrade-with-warning input shape :func:`federate` expects)."""
    out: List[Tuple[str, Optional[str]]] = []
    for ep in endpoints:
        try:
            out.append((ep, scrape_endpoint(ep, timeout_s=timeout_s)))
        except Exception:
            out.append((ep, None))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sheep-fleet-metrics",
        description="Merge N sheepd replica scrapes into one exact "
                    "fleet exposition document (counters sum, gauges "
                    "gain a replica label, same-boundary histogram "
                    "buckets add).")
    ap.add_argument("endpoint", nargs="*",
                    help="replica endpoints: unix socket path, "
                         "http(s)://host:port/metrics URL, or a saved "
                         "scrape text file")
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated endpoints (sheeptop-style "
                         "alternative to positionals)")
    ap.add_argument("--timeout", type=float, default=10.0,
                    help="per-replica scrape timeout seconds")
    ap.add_argument("--quantile", action="append", default=[],
                    metavar="NAME:Q[:label=v,...]",
                    help="also print a fleet quantile over the merged "
                         "histogram NAME (repeatable), e.g. "
                         "sheepd_request_latency_seconds:0.99 or "
                         "...:0.5:tenant=t0")
    ap.add_argument("--json", action="store_true",
                    help="emit the federate record as JSON instead of "
                         "exposition text")
    args = ap.parse_args(argv)

    endpoints = list(args.endpoint)
    if args.endpoints:
        endpoints += [e.strip() for e in args.endpoints.split(",")
                      if e.strip()]
    if not endpoints:
        ap.error("no endpoints given")

    scrapes = scrape_fleet(endpoints, timeout_s=args.timeout)
    try:
        fed = federate(scrapes)
    except FederationError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for w in fed["warnings"]:
        print(f"warning: {w}", file=sys.stderr)
    if not fed["answered"]:
        print("error: no replica answered a scrape", file=sys.stderr)
        return 1

    quantiles = {}
    for spec in args.quantile:
        parts = spec.split(":")
        if len(parts) < 2:
            ap.error(f"--quantile wants NAME:Q, got {spec!r}")
        name, q = parts[0], float(parts[1])
        match = None
        if len(parts) > 2 and parts[2]:
            match = dict(kv.split("=", 1)
                         for kv in parts[2].split(","))
        quantiles[spec] = fleet_quantile(fed, name, q, match)

    if args.json:
        json.dump({"replicas": fed["replicas"],
                   "answered": fed["answered"],
                   "warnings": fed["warnings"],
                   "quantiles": quantiles,
                   "samples": {n: [[ls, v] for ls, v in rows]
                               for n, rows in fed["samples"].items()}},
                  sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        sys.stdout.write(render_federated(fed))
        for spec, v in quantiles.items():
            print(f"# quantile {spec} = "
                  f"{'NaN' if v is None else _fmt_value(round(v, 9))}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
