"""Heartbeat thread: periodic progress records for streaming builds.

A multi-hour soak is a black box between launch and the final scores
line unless something emits while it runs; the heartbeat makes a DEAD
run distinguishable from a SLOW one (last heartbeat age vs cadence).
Each record carries the instrumented loops' racily-updated progress
fields (phase, chunks done/total, approximate edges done), a computed
edges/sec + ETA, the counter registry snapshot (dispatch counts live,
not just at the end), and the device-memory high-water mark where the
platform exposes one:

    {"event": "heartbeat", "ts": ..., "seq": 3, "phase": "build",
     "chunks_done": 12, "chunks_total": 64, "edges_done": 100663296,
     "edges_per_sec": 3.1e6, "eta_s": 140.9,
     "counters": {"host_syncs": 13, "device_rounds": 29, ...},
     "memory": {"peak_bytes_in_use": ..., ...}}

``stop()`` always emits one final record (``"final": true``) after the
thread has joined, so even a run faster than the cadence leaves >= 1
heartbeat in the trace.
"""

from __future__ import annotations

import threading
import time

from sheep_tpu.utils.metrics import device_memory_stats


class Heartbeat:
    """Daemon thread emitting ``heartbeat`` events every ``interval_s``
    seconds through ``tracer`` until :meth:`stop`."""

    def __init__(self, tracer, interval_s: float, memory: bool = True,
                 service=None):
        self.tracer = tracer
        self.interval = max(0.05, float(interval_s))
        self._memory = memory
        # optional service-pressure provider (ISSUE 11): inside sheepd
        # the daemon passes the scheduler's live queue-depth/active-job
        # sampler, so soak logs show SERVICE pressure per beat, not
        # just per-run progress. Must be cheap and non-blocking-ish
        # (it runs on the heartbeat thread every beat).
        self._service = service
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="sheep-heartbeat", daemon=True)
        self._seq = 0
        self._last = None  # (perf_counter, edges_done) of the last beat

    def start(self) -> "Heartbeat":
        self._last = (time.perf_counter(), 0)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the thread and emit the final flush (after the join, so
        the final record cannot race a periodic one)."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2 * self.interval + 5)
        try:
            self._beat(final=True)
        except Exception:
            # teardown runs inside the CLI's finally: a failed final
            # flush must not mask the run's real exit status
            pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._beat()
            except Exception:
                # one transient emit failure (disk blip, flaky NFS) must
                # not kill the thread for the rest of a multi-hour soak:
                # silenced heartbeats would read as a DEAD run — the
                # exact misdiagnosis this feature exists to prevent.
                # Keep ticking; the next beat retries the sink.
                continue

    def _beat(self, final: bool = False) -> None:
        tr = self.tracer
        prog = dict(tr.progress)  # racy copy by design; fields are scalars
        now = time.perf_counter()
        rec = {"seq": self._seq}
        rec.update(prog)
        edges = prog.get("edges_done")
        if isinstance(edges, (int, float)) and self._last is not None:
            t0, e0 = self._last
            # rate over the inter-beat window; a phase change resets
            # edges_done, making the delta negative — skip those beats
            if now > t0 and edges >= e0:
                rate = (edges - e0) / (now - t0)
                if rate > 0:
                    rec["edges_per_sec"] = round(rate, 1)
                    total = prog.get("edges_total")
                    if isinstance(total, (int, float)) and total >= edges:
                        rec["eta_s"] = round((total - edges) / rate, 1)
            self._last = (now, edges)
        if self._service is not None:
            try:
                svc = self._service()
            except Exception:
                svc = None  # a wedged sampler must not kill the beat
            if svc:
                rec.update(svc)
        counters = tr.counters.snapshot()
        if counters:
            rec["counters"] = counters
        if self._memory:
            mem = device_memory_stats()
            if mem:
                rec["memory"] = mem
        if final:
            rec["final"] = True
        tr.emit("heartbeat", **rec)
        self._seq += 1
