"""Hierarchical span tracer + counter registry (the obs core).

A run traced through this module renders as a TREE, not a flat phase
list: every span carries an id and its parent's id, so
``span("build") > span("segment", i=k) > span("dispatch")`` nests in
the JSONL exactly as it nested in time. Two event kinds:

    {"event": "span_start", "ts": ..., "span": "build", "id": 3,
     "parent": 1, ...attrs}
    {"event": "span_end", "ts": ..., "span": "build", "id": 3,
     "parent": 1, "secs": 8.21, "counters": {"host_syncs": 4, ...}}

``counters`` on span_end is the DELTA of the tracer's registry between
span entry and exit — the ad-hoc ``host_syncs``/``device_rounds``/fold
diagnostics become named metrics sampled at span boundaries. A span
that never ends (process killed mid-build) leaves its span_start as
the last word on where the run died — ``tools/trace_report.py`` flags
those, which is how a dead soak is distinguished from a slow one after
the fact (the round-5 s30 soak died silently for lack of exactly
this).

Spans are context managers, but every span also exposes explicit
``start()``/``end()`` so hot loops can bracket work without
re-indenting (``sp = obs.begin("segment", i=k); ...; sp.end()``).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import IO, Optional, Union

from sheep_tpu.utils.metrics import MetricsWriter


class CounterRegistry(dict):
    """Named counters/gauges. A plain-dict subclass on purpose: the
    existing ad-hoc stats dicts (``stats["host_syncs"] = ...`` in
    ops/elim.py and the pipelines) absorb without adaptation, and
    ``snapshot``/``delta`` give the span tracer and heartbeat a
    queryable view."""

    def inc(self, name: str, v=1) -> None:
        self[name] = self.get(name, 0) + v

    def gauge(self, name: str, v) -> None:
        self[name] = v

    def absorb(self, stats: dict) -> None:
        """Overwrite-merge a CUMULATIVE stats dict. The elim-ops/pipeline
        counters grow monotonically within a run, so overwriting makes
        absorb idempotent — callers may re-absorb the same dict every
        segment and the registry always holds the latest totals."""
        for k, v in stats.items():
            self[k] = v

    def snapshot(self) -> dict:
        return dict(self)

    @staticmethod
    def delta(before: dict, after: dict) -> dict:
        """Numeric keys: after - before (omitted when zero). Non-numeric
        keys (mode strings etc.): included when changed."""
        out = {}
        for k, v in after.items():
            v0 = before.get(k, 0 if isinstance(v, (int, float))
                            and not isinstance(v, bool) else None)
            if (isinstance(v, (int, float)) and not isinstance(v, bool)
                    and isinstance(v0, (int, float))
                    and not isinstance(v0, bool)):
                d = v - v0
                if d:
                    out[k] = round(d, 6) if isinstance(d, float) else d
            elif v0 != v:
                out[k] = v
        return out


class StatsAccumulator:
    """Per-run bridge from one CUMULATIVE stats dict into a registry.

    The ad-hoc stats dicts grow monotonically WITHIN one partition
    call, but each call starts a fresh dict — several calls under one
    tracer (hierarchy levels, partition_multi legs, appended CLI runs)
    must SUM into the registry, not overwrite it (overwrite would emit
    negative span deltas and report only the last call's totals).
    Each ``absorb`` adds only the increment since THIS accumulator's
    previous absorb; create one per stats dict, at the start of the
    run that owns it. Non-numeric values (mode strings) overwrite."""

    __slots__ = ("_reg", "_last")

    def __init__(self, registry: CounterRegistry):
        self._reg = registry
        self._last: dict = {}

    def absorb(self, stats: dict) -> None:
        for k, v in stats.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                prev = self._last.get(k, 0)
                if not isinstance(prev, (int, float)) \
                        or isinstance(prev, bool):
                    prev = 0
                d = v - prev
                if d:
                    self._reg[k] = self._reg.get(k, 0) + d
            else:
                self._reg[k] = v
            self._last[k] = v


class NullStatsAccumulator:
    __slots__ = ()

    def absorb(self, stats: dict) -> None:
        pass


NULL_STATS = NullStatsAccumulator()

# sentinel: "no explicit parent given — derive from the thread-local
# stack" (None is a valid explicit parent meaning "root")
_STACK_PARENT = object()


class Span:
    """One traced interval. Usable as a context manager or via explicit
    ``start()``/``end()`` (unbalanced on purpose when the process dies —
    see module docstring).

    ``parent``/``attach``: by default a span parents to the enclosing
    span on ITS thread's stack and joins that stack. A DETACHED span
    (``attach=False``, parent given explicitly) does neither — it is the
    form for interleaved long-lived intervals that do not nest in time
    on any one thread (the server scheduler's per-job spans: job A's
    root must not become the parent of job B's phases just because both
    jobs step on the scheduler thread)."""

    __slots__ = ("_tracer", "name", "attrs", "id", "parent", "_t0",
                 "_snap", "_done", "_parent_arg", "_attach")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict,
                 parent=_STACK_PARENT, attach: bool = True):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.id = None
        self.parent = None
        self._t0 = 0.0
        self._snap: dict = {}
        self._done = False
        self._parent_arg = parent
        self._attach = attach

    def start(self) -> "Span":
        tr = self._tracer
        self.parent = tr._current_id() \
            if self._parent_arg is _STACK_PARENT else self._parent_arg
        self.id = tr._next_id()
        self._snap = tr.counters.snapshot()
        with tr._balance_lock:  # spans may start on worker threads
            tr._open_spans += 1
        if self._attach:
            tr._push(self.id)
        tr.emit("span_start", span=self.name, id=self.id,
                parent=self.parent, **self.attrs)
        self._t0 = time.perf_counter()
        return self

    def annotate(self, **attrs) -> None:
        """Attach attributes to a RUNNING span so its span_end record
        carries them (the span_start already went out). The quality
        ledger's use case (ISSUE 13): the refine span learns its
        starting cut on the first scoring pass, rounds before the span
        closes — annotate-then-end puts the number on the interval it
        belongs to instead of threading it to the end() call site."""
        self.attrs.update(attrs)

    def end(self, **extra) -> None:
        if self._done or self.id is None:
            return
        self._done = True
        tr = self._tracer
        secs = time.perf_counter() - self._t0
        with tr._balance_lock:
            tr._open_spans -= 1
        if self._attach:
            tr._pop(self.id)
        fields = dict(span=self.name, id=self.id, parent=self.parent,
                      secs=round(secs, 6), **self.attrs)
        fields.update(extra)
        delta = CounterRegistry.delta(self._snap, tr.counters)
        if delta:
            fields["counters"] = delta
        tr.emit("span_end", **fields)

    def __enter__(self) -> "Span":
        return self.start()

    def __exit__(self, et, ev, tb) -> bool:
        self.end(**({"error": et.__name__} if et is not None else {}))
        return False


class NullSpan:
    """The disabled-tracing span: every operation is a no-op on a shared
    singleton, so instrumentation left in hot loops costs one global
    read + one attribute call when tracing is off."""

    __slots__ = ()

    def start(self) -> "NullSpan":
        return self

    def annotate(self, **attrs) -> None:
        pass

    def end(self, **extra) -> None:
        pass

    def __enter__(self) -> "NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = NullSpan()


class Tracer:
    """JSONL span/counter/heartbeat sink for one run.

    Thread model: span ids come from an atomic counter and the span
    stack is thread-local (a span opened on a worker thread parents to
    that thread's enclosing span, or to nothing). ``progress`` is a
    plain dict updated racily by the instrumented loops and read by the
    heartbeat thread — single fields only, no cross-field invariants.
    The underlying MetricsWriter serializes concurrent emits."""

    def __init__(self, dest: Union[str, IO]):
        self._mw = MetricsWriter(dest)
        self.counters = CounterRegistry()
        self.progress: dict = {}
        self.heartbeat = None  # owner-managed Heartbeat, if any
        self._local = threading.local()
        self._ids = itertools.count(1)
        self._closed = False
        self._open_spans = 0  # begun minus ended, across all threads
        self._balance_lock = threading.Lock()

    # -- events ------------------------------------------------------------
    def emit(self, event: str, **fields) -> None:
        self._mw.emit(event, **fields)

    # -- spans -------------------------------------------------------------
    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def begin(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs).start()

    def begin_detached(self, name: str, parent=None,
                       remote_parent=None, **attrs) -> Span:
        """Start a DETACHED span: explicit ``parent`` span id (or None
        for a root), never on any thread's span stack — for intervals
        that interleave in time instead of nesting (see Span).

        ``remote_parent`` (ISSUE 18) is a CROSS-PROCESS parent:
        ``{"trace": <hex trace id>, "span": <hex remote span id>}``
        from a propagated wire trace context. The local tree is
        untouched (``parent`` still names the local parent id); the
        span records additionally carry ``trace`` and
        ``remote_parent`` attrs, which is what lets
        ``tools/trace_report.py --stitch`` graft this process's
        subtree under the originating client span in a DIFFERENT
        process's trace file. An all-zero remote span id means "the
        caller had no span of its own" — the trace id still lands."""
        if remote_parent:
            attrs = dict(attrs)
            tid = remote_parent.get("trace")
            if tid:
                attrs.setdefault("trace", tid)
            rp = remote_parent.get("span")
            if rp and set(str(rp)) != {"0"}:
                attrs.setdefault("remote_parent", str(rp))
        return Span(self, name, attrs, parent=parent,
                    attach=False).start()

    def current_span_id(self) -> Optional[int]:
        """The calling thread's innermost open span id (None at root)
        — what a client stamps into an outgoing wire trace context as
        the remote parent span (ISSUE 18)."""
        return self._current_id()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _current_id(self) -> Optional[int]:
        st = self._stack()
        return st[-1] if st else None

    def _next_id(self) -> int:
        return next(self._ids)  # itertools.count: atomic under the GIL

    def _push(self, span_id: int) -> None:
        self._stack().append(span_id)

    def _pop(self, span_id: int) -> None:
        st = self._stack()
        # tolerate out-of-order ends (a caller leaking a handle must not
        # corrupt every later parent attribution): pop through to ours
        while st and st[-1] != span_id:
            st.pop()
        if st:
            st.pop()

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Flush the final counter totals (one ``counters`` event — the
        queryable end-state tools read without re-deriving span deltas)
        and close the sink.

        Under ``SHEEP_SANITIZE=1`` a nonzero open-span count here
        raises: an unbalanced span at a CLEAN close is a leaked handle
        (the deliberate unbalanced-on-death case never reaches close,
        so the forensic value of unclosed spans is untouched)."""
        if self._closed:
            return
        self._closed = True
        if self.counters:
            self.emit("counters", **self.counters.snapshot())
        open_spans = self._open_spans
        if open_spans:
            from sheep_tpu.analysis import sanitize

            if sanitize.enabled():
                self._mw.close()
                raise sanitize.SanitizeError(
                    f"tracer closed with {open_spans} span(s) begun "
                    f"but never ended — a leaked span handle (run "
                    f"tools/trace_report.py on the trace to see which)")
        self._mw.close()

    def __enter__(self) -> "Tracer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
