"""Run manifest: what exactly ran, captured at launch.

One ``manifest`` event per traced run, so any trace file is
self-describing — config, backend, device/mesh topology, jax/jaxlib
versions, git SHA — and two captures are comparable without artifact
archaeology (the BENCH contract's lesson, applied to every run).
Collection is best-effort throughout: a broken accelerator runtime or
a git-less checkout degrades fields to null, never takes the run down.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional


def _git_sha(repo_dir: str) -> Optional[str]:
    """HEAD commit (short) — ``git`` first, manual .git parse fallback
    so a container without the git binary still records provenance."""
    try:
        r = subprocess.run(["git", "rev-parse", "--short=12", "HEAD"],
                           cwd=repo_dir, capture_output=True, text=True,
                           timeout=5)
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip()
    except Exception:
        pass
    try:
        head_path = os.path.join(repo_dir, ".git", "HEAD")
        with open(head_path) as f:
            head = f.read().strip()
        if head.startswith("ref: "):
            ref = os.path.join(repo_dir, ".git", *head[5:].split("/"))
            with open(ref) as f:
                return f.read().strip()[:12]
        return head[:12]
    except Exception:
        return None


def _jsonable_config(config: dict) -> dict:
    """argparse namespaces carry only simple values, but be defensive:
    anything not JSON-representable is stringified rather than crashing
    the manifest emit."""
    import json

    out = {}
    for k, v in config.items():
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = str(v)
    return out


def collect_manifest(config: Optional[dict] = None,
                     backend: Optional[str] = None) -> dict:
    """The manifest record body. Device topology and versions come from
    jax when it is importable and initialized cleanly; every field
    degrades to null/absent rather than raising."""
    import platform as _platform

    repo_dir = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    rec: dict = {
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "hostname": _platform.node(),
        "pid": os.getpid(),
        "git_sha": _git_sha(repo_dir),
    }
    if backend is not None:
        rec["backend"] = backend
    if config is not None:
        rec["config"] = _jsonable_config(dict(config))
    try:
        import numpy as np

        rec["numpy_version"] = np.__version__
    except Exception:
        pass
    try:
        import jax

        rec["jax_version"] = jax.__version__
        try:
            import jaxlib

            rec["jaxlib_version"] = jaxlib.__version__
        except Exception:
            rec["jaxlib_version"] = None
        # topology: initializes the backend if nothing else has yet —
        # manifests are emitted by runs that are about to anyway
        rec["platform"] = jax.default_backend()
        rec["process_index"] = jax.process_index()
        rec["process_count"] = jax.process_count()
        rec["device_count"] = jax.device_count()
        rec["local_device_count"] = jax.local_device_count()
        rec["devices"] = [
            {"id": d.id, "kind": getattr(d, "device_kind", "?"),
             "process": d.process_index}
            for d in jax.local_devices()]
    except Exception as e:
        rec["jax_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    return rec


def emit_manifest(tracer, config: Optional[dict] = None,
                  backend: Optional[str] = None) -> dict:
    rec = collect_manifest(config=config, backend=backend)
    tracer.emit("manifest", **rec)
    return rec
