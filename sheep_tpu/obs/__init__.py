"""sheep_tpu.obs — the observability spine (ISSUE 3 tentpole).

One module-level tracer that everything threads through:

- **spans** — hierarchical timed intervals emitted as JSONL
  (``span_start``/``span_end`` with parent ids), so a run renders as a
  tree (``tools/trace_report.py``) instead of a flat phase list;
- **counters** — a registry the ad-hoc ``host_syncs``/``device_rounds``
  /fold diagnostics absorb into, sampled as deltas at span boundaries
  and live by the heartbeat;
- **heartbeat** — a thread emitting periodic progress records so a
  multi-hour soak is observable while running (and a dead run is
  distinguishable from a slow one);
- **manifest** — config/topology/version/git-SHA provenance on every
  traced run;
- **metrics** (ISSUE 11) — a typed live registry (counters, gauges,
  fixed-bucket histograms) with Prometheus text rendering, the
  scrape-able face of the same numbers (``obs/metrics.py``);
- **flight recorder** (ISSUE 11) — always-on bounded rings of the
  last N events per job, fed by :func:`event` alongside the tracer
  and dumped to the trace sink on failure/fault/shutdown
  (``obs/flightrec.py``).

Instrumentation calls are UNCONDITIONAL at the call sites (backends,
pipelines, CLI) and near-free when tracing is off: every facade
function reads one module global and returns a shared no-op. Install a
tracer (CLI ``--trace``, or :func:`tracing` in tests/tools) and the
same call sites produce the full trace.

    from sheep_tpu import obs

    acc = obs.stats_accumulator()            # one per stats dict
    with obs.span("build"):
        for i, chunk in enumerate(chunks):
            sp = obs.begin("segment", i=i)
            ...fold...
            acc.absorb(build_stats)          # counter increments -> registry
            obs.progress(chunks_done=i + 1)  # heartbeat inputs
            sp.end(rounds=r)
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import IO, Optional, Union

from sheep_tpu.obs.flightrec import FlightRecorder  # noqa: F401
from sheep_tpu.obs.heartbeat import Heartbeat  # noqa: F401
from sheep_tpu.obs.manifest import collect_manifest, emit_manifest  # noqa: F401
from sheep_tpu.obs.metrics import MetricRegistry  # noqa: F401
from sheep_tpu.obs.tracer import (NULL_SPAN, NULL_STATS, CounterRegistry,  # noqa: F401
                                  NullSpan, Span, StatsAccumulator, Tracer)

_TRACER: Optional[Tracer] = None
_FLIGHT: Optional[FlightRecorder] = None


def install_flight(recorder: FlightRecorder) -> FlightRecorder:
    """Make ``recorder`` the process-wide flight recorder: every
    :func:`event` also lands in its bounded rings (ISSUE 11). Unlike
    the tracer this is always-on-capable — it costs one deque append
    per event and performs no I/O until a dump."""
    global _FLIGHT
    _FLIGHT = recorder
    return recorder


def uninstall_flight() -> Optional[FlightRecorder]:
    global _FLIGHT
    fr, _FLIGHT = _FLIGHT, None
    return fr


def get_flight() -> Optional[FlightRecorder]:
    return _FLIGHT


def flight_job() -> Optional[str]:
    """The calling thread's flight-recorder job context (None without
    a recorder or outside any context) — capture this before spawning
    a worker thread, then re-enter it there with
    :func:`flight_job_context`."""
    f = _FLIGHT
    return f.current_job() if f is not None else None


def flight_job_context(job_id: Optional[str]):
    """Enter ``job_id`` as the calling thread's flight context (no-op
    context manager when tracing-by-ring is off or job_id is None)."""
    from contextlib import nullcontext

    f = _FLIGHT
    if f is None or job_id is None:
        return nullcontext()
    return f.job_context(job_id)


def install(tracer: Tracer) -> Tracer:
    """Make ``tracer`` the process-wide active tracer."""
    global _TRACER
    _TRACER = tracer
    return tracer


def uninstall() -> Optional[Tracer]:
    """Deactivate (and return) the active tracer without closing it."""
    global _TRACER
    t, _TRACER = _TRACER, None
    return t


def get_tracer() -> Optional[Tracer]:
    return _TRACER


def enabled() -> bool:
    return _TRACER is not None


def span(name: str, **attrs):
    """Context-manager span under the active tracer (shared no-op when
    tracing is off)."""
    t = _TRACER
    return t.span(name, **attrs) if t is not None else NULL_SPAN


def begin(name: str, **attrs):
    """Explicitly-started span (``.end()`` when done) — the
    no-reindent form for instrumenting existing phase blocks."""
    t = _TRACER
    return t.begin(name, **attrs) if t is not None else NULL_SPAN


def begin_detached(name: str, parent=None, remote_parent=None, **attrs):
    """Explicitly-started DETACHED span: parented to the given span id
    (or a root when None) instead of the calling thread's span stack,
    and never pushed onto that stack. The form for intervals that
    interleave rather than nest — e.g. per-job spans on the server
    scheduler thread. ``parent`` accepts a Span too (its id is used).
    ``remote_parent`` is a propagated cross-process trace context
    ``{"trace": ..., "span": ...}`` (see Tracer.begin_detached)."""
    t = _TRACER
    if t is None:
        return NULL_SPAN
    if isinstance(parent, (Span, NullSpan)):
        parent = getattr(parent, "id", None)
    return t.begin_detached(name, parent=parent,
                            remote_parent=remote_parent, **attrs)


def current_span_id():
    """The calling thread's innermost open span id under the active
    tracer (None when untraced or at root) — the remote-parent half of
    an outgoing wire trace context (ISSUE 18)."""
    t = _TRACER
    return t.current_span_id() if t is not None else None


def absorb(stats: dict) -> None:
    """One-shot overwrite-merge of a stats dict into the registry (see
    CounterRegistry.absorb). For the per-chunk absorption of a RUN's
    cumulative stats dict use :func:`stats_accumulator` — re-absorbing
    fresh dicts from several runs through THIS function would overwrite
    totals instead of summing them."""
    t = _TRACER
    if t is not None:
        t.counters.absorb(stats)


def stats_accumulator():
    """A per-run :class:`StatsAccumulator` bound to the active tracer's
    registry (shared no-op when tracing is off). Create one per
    cumulative stats dict, at the start of the run that owns it."""
    t = _TRACER
    return StatsAccumulator(t.counters) if t is not None else NULL_STATS


def inc(name: str, v=1) -> None:
    t = _TRACER
    if t is not None:
        t.counters.inc(name, v)


def gauge(name: str, v) -> None:
    t = _TRACER
    if t is not None:
        t.counters.gauge(name, v)


def progress(**fields) -> None:
    """Update the heartbeat's progress fields (racy scalar writes)."""
    t = _TRACER
    if t is not None:
        t.progress.update(fields)


def chunk_progress(idx: int, chunk_edges: int, edges_total=None) -> None:
    """The streamed-chunk loops' one-line progress update: chunks done
    plus the approximate edges_done they imply (capped at the stream
    total when one is cheaply known)."""
    t = _TRACER
    if t is None:
        return
    done = idx * chunk_edges
    t.progress.update(chunks_done=idx,
                      edges_done=min(done, edges_total)
                      if edges_total else done)


def event(name: str, **fields) -> None:
    """Emit a free-form event through the active tracer (no-op off)
    AND into the installed flight recorder's bounded rings (no-op
    without one) — the one call site both sinks share."""
    t = _TRACER
    if t is not None:
        t.emit(name, **fields)
    f = _FLIGHT
    if f is not None:
        f.record(name, fields)


@contextmanager
def tracing(dest: Union[str, IO], heartbeat_secs: Optional[float] = None):
    """Scoped tracing for tests/tools: install a fresh Tracer on
    ``dest`` (path or writable handle), optionally with a heartbeat,
    restore the previous tracer and close on exit."""
    global _TRACER
    prev = _TRACER
    t = Tracer(dest)
    _TRACER = t
    hb = Heartbeat(t, heartbeat_secs).start() if heartbeat_secs else None
    try:
        yield t
    finally:
        if hb is not None:
            hb.stop()
        _TRACER = prev
        t.close()
