"""Typed live-metric registry with Prometheus text rendering (ISSUE 11
tentpole).

The obs spine's :class:`~sheep_tpu.obs.tracer.CounterRegistry` is a
*trace* artifact: its values surface as span-boundary deltas and
heartbeat snapshots inside a JSONL file that tools read after the fact.
A scraper (or the ROADMAP's future membudget-aware router) needs the
opposite shape — typed, labeled, LIVE series answered at poll time:

- :class:`Counter` — monotonically increasing totals (jobs submitted,
  admission rejections, dispatch retries);
- :class:`Gauge` — point-in-time levels (queue depth, reserved bytes,
  HBM headroom);
- :class:`Histogram` — fixed-bucket latency distributions with
  cumulative ``_bucket``/``_sum``/``_count`` rendering and quantile
  estimation, the SLO primitive (per-tenant request latency
  queued->done).

All three support Prometheus-style labels; :class:`MetricRegistry`
owns them and renders the exposition text
(``text/plain; version=0.0.4``) that the sheepd ``metrics`` verb and
the ``GET /metrics`` HTTP listener answer. ``add_collector`` registers
scrape-time callbacks so values that already live elsewhere — the
scheduler's queue/reservation state, the active tracer's
CounterRegistry, jax device-memory stats — are absorbed as live gauges
at poll time instead of being mirrored on every mutation.

Deliberately dependency-free (stdlib only): the thin client and
``sheeptop`` parse/render these without an accelerator stack, and the
disabled path costs nothing (no instrument exists unless something
created it).
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# SLO-ish request-latency buckets: sub-10ms protocol ops through
# multi-minute cold builds. Fixed (not configurable per call site) so
# series from different daemons always merge.
DEFAULT_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0, 600.0)

# Cut-ratio buckets for the sheep_quality_* histograms (ISSUE 13):
# log-ish spacing over [0, 1] — planted-recovery cuts live at 0.01-0.1,
# expander cuts at 0.9+, and the interesting regressions are small
# relative moves near the bottom. Fixed for the same merge reason.
DEFAULT_RATIO_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.15, 0.25, 0.4,
    0.6, 0.8, 0.95)

# Balance buckets: 1.0 is perfect, the --balance contract band is
# 1.05-1.3, and past 2 the split is degenerate.
DEFAULT_BALANCE_BUCKETS = (
    1.01, 1.02, 1.05, 1.1, 1.2, 1.3, 1.5, 2.0, 3.0, 5.0)

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Coerce an arbitrary counter key into a legal metric name."""
    name = _NAME_FIX.sub("_", str(name))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def _escape_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v) -> str:
    """Prometheus sample value: integers without a trailing .0, floats
    via repr (full precision), +Inf spelled the exposition way."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(names: Tuple[str, ...], values: Tuple) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    """Shared shape: one metric family = name + help + label names +
    a dict of label-value tuples -> sample state. The registry's lock
    guards every mutation (scrapes race increments from the dispatch
    and handler threads). Scalar-valued kinds (counter/gauge) share
    the render/value implementations; Histogram overrides render."""

    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Tuple[str, ...], lock: threading.Lock):
        if not _NAME_OK.match(name):
            raise ValueError(f"bad metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._samples: Dict[Tuple, object] = {}

    def _key(self, labels: dict) -> Tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} wants labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(labels[n] for n in self.labelnames)

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()

    def value(self, **labels):
        with self._lock:
            return self._samples.get(self._key(labels), 0)

    def render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted(self._samples.items())
        for key, v in items:
            out.append(f"{self.name}"
                       f"{_label_str(self.labelnames, key)} {_fmt(v)}")


class Counter(_Metric):
    """Monotonic total. ``inc`` only — a counter that can go down is a
    gauge wearing the wrong type and breaks every rate() query."""

    kind = "counter"

    def inc(self, v=1, **labels) -> None:
        if v < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0) + v


class Gauge(_Metric):
    """Point-in-time level; ``set`` wins, ``inc``/``dec`` for levels
    maintained by paired events."""

    kind = "gauge"

    def set(self, v, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._samples[key] = v

    def inc(self, v=1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0) + v

    def dec(self, v=1, **labels) -> None:
        self.inc(-v, **labels)

    def remove(self, **labels) -> None:
        """Drop one labeled series (a finished job's progress gauge
        must leave the scrape, not freeze at its last value)."""
        with self._lock:
            self._samples.pop(self._key(labels), None)


class _HistState:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (NOT cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bucket histogram. ``buckets`` are the finite upper bounds
    (ascending); a +Inf bucket is always appended. Prometheus ``le``
    semantics: an observation equal to a bound lands in THAT bucket
    (v <= upper). Rendering is cumulative, as scrapers expect."""

    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: Optional[Iterable[float]] = None):
        super().__init__(name, help, labelnames, lock)
        bs = tuple(float(b) for b in
                   (DEFAULT_LATENCY_BUCKETS if buckets is None
                    else buckets))
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])) \
                or any(math.isinf(b) for b in bs):
            raise ValueError(f"histogram {name}: buckets must be "
                             f"finite strictly-ascending uppers; "
                             f"+Inf is implicit")
        self.buckets = bs  # finite uppers; index len(bs) is +Inf

    def observe(self, v, **labels) -> None:
        key = self._key(labels)
        v = float(v)
        # bisect_left gives the first bucket whose upper >= v, which is
        # exactly `le` membership; past the end = the +Inf bucket
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            st = self._samples.get(key)
            if st is None:
                st = self._samples[key] = _HistState(
                    len(self.buckets) + 1)
            st.counts[idx] += 1
            st.sum += v
            st.count += 1

    def snapshot(self, **labels) -> Optional[dict]:
        """{"cum": cumulative counts incl +Inf, "sum": s, "count": n}
        for one labeled series, or None when never observed."""
        with self._lock:
            st = self._samples.get(self._key(labels))
            if st is None:
                return None
            counts = list(st.counts)
            total, s = st.count, st.sum
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return {"cum": cum, "sum": s, "count": total}

    def quantile(self, q: float, **labels) -> Optional[float]:
        snap = self.snapshot(**labels)
        if snap is None or snap["count"] == 0:
            return None
        return quantile_from_cumulative(self.buckets, snap["cum"], q)

    def render(self, out: List[str]) -> None:
        with self._lock:
            items = [(k, list(st.counts), st.sum, st.count)
                     for k, st in sorted(self._samples.items())]
        uppers = [_fmt(b) for b in self.buckets] + ["+Inf"]
        for key, counts, s, n in items:
            acc = 0
            for upper, c in zip(uppers, counts):
                acc += c
                names = self.labelnames + ("le",)
                out.append(f"{self.name}_bucket"
                           f"{_label_str(names, key + (upper,))} {acc}")
            ls = _label_str(self.labelnames, key)
            out.append(f"{self.name}_sum{ls} {_fmt(s)}")
            out.append(f"{self.name}_count{ls} {n}")


def quantile_from_cumulative(uppers, cum_counts, q: float
                             ) -> Optional[float]:
    """Estimate the q-quantile from cumulative bucket counts (finite
    ``uppers`` + one trailing +Inf count), linearly interpolating
    within the landing bucket — the promql ``histogram_quantile``
    estimator, reusable by sheeptop on parsed scrape text. An estimate
    that lands in the +Inf bucket returns the largest finite upper
    (the honest answer: "at least this")."""
    if not cum_counts:
        return None
    total = cum_counts[-1]
    if total <= 0:
        return None
    q = min(1.0, max(0.0, float(q)))
    rank = q * total
    for i, c in enumerate(cum_counts):
        if c >= rank and c > 0:
            if i >= len(uppers):     # +Inf bucket
                return float(uppers[-1]) if uppers else None
            lo = float(uppers[i - 1]) if i > 0 else 0.0
            hi = float(uppers[i])
            prev = cum_counts[i - 1] if i > 0 else 0
            in_bucket = c - prev
            if in_bucket <= 0:
                return hi
            frac = (rank - prev) / in_bucket
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
    return float(uppers[-1]) if uppers else None


class MetricRegistry:
    """Typed metric families + scrape-time collectors, rendered as one
    Prometheus text document. ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent by name; a kind or label mismatch on an
    existing name raises — two call sites disagreeing about a metric's
    type is a bug, not a merge)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, _Metric]" = {}
        self._collectors: List[Callable[[], object]] = []

    # -- registration --------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) \
                        or m.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.labelnames}")
                return m
            m = cls(name, help, labelnames, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def add_collector(self, fn: Callable[[], object]) -> None:
        """Register a scrape-time callback. It may return a plain
        ``{name: value}`` dict (rendered as unlabeled gauges) or an
        iterable of ``(name, labels_dict, value)`` samples. A collector
        that raises is skipped for that scrape (a flaky device-memory
        probe must not take down the whole exposition)."""
        with self._lock:
            self._collectors.append(fn)

    # -- rendering -----------------------------------------------------
    def render(self) -> str:
        """The full exposition document: registered families in
        registration order, then collector gauges grouped by name."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        out: List[str] = []
        for m in metrics:
            if m.help:
                out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            m.render(out)
        collected: "Dict[str, List[Tuple[Tuple, Tuple, object]]]" = {}
        for fn in collectors:
            try:
                produced = fn()
            except Exception:
                continue  # one flaky probe must not kill the scrape
            if produced is None:
                continue
            if isinstance(produced, dict):
                produced = [(k, {}, v) for k, v in produced.items()]
            for name, labels, value in produced:
                if not isinstance(value, (int, float)) \
                        or isinstance(value, bool):
                    continue
                name = sanitize_name(name)
                names = tuple(sorted(labels))
                vals = tuple(labels[n] for n in names)
                collected.setdefault(name, []).append(
                    (names, vals, value))
        for name in sorted(collected):
            out.append(f"# TYPE {name} gauge")
            for names, vals, value in sorted(collected[name]):
                out.append(f"{name}{_label_str(names, vals)} "
                           f"{_fmt(value)}")
        return "\n".join(out) + "\n"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r'\\(.)')


def _unescape_label(s: str) -> str:
    # one scan, not sequential replaces: '\\' followed by 'n' is a
    # literal backslash + n, and a chained .replace would eat half of
    # the escaped backslash and fabricate a newline
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), s)


def parse_prometheus(text: str) -> Dict[str, List[Tuple[dict, float]]]:
    """Parse exposition text back into ``{name: [(labels, value)]}`` —
    what sheeptop (and tests) consume. Tolerant: comment and
    unparseable lines are skipped, values that aren't numbers are
    skipped. ``+Inf``/``NaN`` come back as the float they are."""
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        raw = m.group("value")
        try:
            value = float(raw.replace("+Inf", "inf")
                          .replace("-Inf", "-inf"))
        except ValueError:
            continue
        labels = {}
        if m.group("labels"):
            for lm in _LABEL_RE.finditer(m.group("labels")):
                labels[lm.group(1)] = _unescape_label(lm.group(2))
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


def histogram_series_quantile(samples: List[Tuple[dict, float]],
                              q: float,
                              match: Optional[dict] = None
                              ) -> Optional[float]:
    """Quantile straight from parsed ``<name>_bucket`` samples (the
    sheeptop path): filter by the ``match`` labels, order by ``le``,
    interpolate. Returns None when no matching buckets exist."""
    rows = []
    for labels, value in samples:
        if match is not None and any(labels.get(k) != v
                                     for k, v in match.items()):
            continue
        le = labels.get("le")
        if le is None:
            continue
        rows.append((float(le.replace("+Inf", "inf")), value))
    if not rows:
        return None
    rows.sort()
    uppers = [u for u, _ in rows if not math.isinf(u)]
    cum = [int(c) for _, c in rows]
    return quantile_from_cumulative(uppers, cum, q)
