"""Always-on bounded flight recorder (ISSUE 11 tentpole).

Full tracing (``--trace``) prices every span and counter onto every
request; with it off, a failed served job used to die with no record
of its last moments. The flight recorder is the middle path: a ring
buffer of the last N span/counter/fault events per job (plus one
global daemon ring), fed by the same :func:`sheep_tpu.obs.event`
facade the fault/retry/scheduler paths already call, cheap enough to
leave on for every request — one dict build and one deque append per
event, zero I/O — and dumped to the trace sink only when something
goes wrong:

- a job reaches FAILED (the scheduler dumps that job's ring);
- a fault is injected (``fault_inject``/``chaos_inject`` events
  trigger an immediate dump, so the ring's tail at the moment of
  injection is preserved even if retries later succeed);
- the daemon shuts down (the global ring + any still-active jobs).

A dump is one ``flight_dump`` trace event carrying the buffered
events; ``tools/trace_report.py --last-errors`` renders them next to
the UNCLOSED-span forensics. With no tracer installed the dump
degrades to one compact stderr line — post-mortem evidence beats
silence even untraced.

Event routing: an event carrying a ``job`` field lands in that job's
ring; otherwise it lands in the ring of the thread's current job
context (the scheduler brackets each dispatch step with
:meth:`FlightRecorder.job_context`, so engine/retry events emitted
mid-step attribute correctly without every call site learning about
jobs), else in the global ring.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import List, Optional

# events that ARE the forensic payload of a dump; recording one
# triggers an immediate dump of the owning ring
DUMP_TRIGGER_EVENTS = frozenset({"fault_inject", "chaos_inject"})

# never recorded: a dump re-entering the recorder would nest dumps
# inside dumps forever
_SELF_EVENTS = frozenset({"flight_dump"})

GLOBAL_RING = "_daemon"


class FlightRecorder:
    """Bounded per-job + global event rings; see module docstring.

    Memory bound is hard: at most ``max_jobs`` job rings of
    ``per_job`` events each plus one ``global_events`` ring — oldest
    job rings are evicted wholesale when a new job would exceed the
    cap, so a resident daemon cannot grow with traffic."""

    def __init__(self, per_job: int = 64, max_jobs: int = 64,
                 global_events: int = 256):
        self.per_job = int(per_job)
        self.max_jobs = int(max_jobs)
        self._lock = threading.Lock()
        self._rings: "OrderedDict[str, deque]" = OrderedDict()
        self._global: deque = deque(maxlen=int(global_events))
        self._ctx = threading.local()
        # job id -> propagated trace id (ISSUE 18): bounded by the
        # ring eviction below, so it cannot grow with traffic either
        self._traces: dict = {}
        self.dumps = 0  # dumps emitted (scrape-able via collector)

    # -- context -------------------------------------------------------
    def current_job(self) -> Optional[str]:
        """The calling thread's job context, if any — captured by
        worker-spawning primitives (utils/prefetch.py) so events
        emitted on THEIR threads still attribute to the job whose step
        created them."""
        return getattr(self._ctx, "job", None)

    @contextmanager
    def job_context(self, job_id: str):
        """Attribute events recorded on THIS thread (without an
        explicit ``job`` field) to ``job_id`` for the duration — the
        scheduler wraps each dispatch step in one."""
        prev = getattr(self._ctx, "job", None)
        self._ctx.job = job_id
        try:
            yield
        finally:
            self._ctx.job = prev

    # -- recording -----------------------------------------------------
    def record(self, kind: str, fields: dict) -> None:
        """One event into the owning ring (see module docstring for
        routing). Called by the obs facade on EVERY obs.event — must
        stay allocation-light and never raise."""
        if kind in _SELF_EVENTS:
            return
        job = fields.get("job") or getattr(self._ctx, "job", None)
        rec = {"t": round(time.time(), 3), "ev": kind}
        rec.update(fields)
        with self._lock:
            if job is None:
                self._global.append(rec)
            else:
                ring = self._rings.get(job)
                if ring is None:
                    ring = deque(maxlen=self.per_job)
                    self._rings[job] = ring
                    while len(self._rings) > self.max_jobs:
                        evicted, _ = self._rings.popitem(last=False)
                        self._traces.pop(evicted, None)
                ring.append(rec)
        if kind in DUMP_TRIGGER_EVENTS:
            self.dump(job, reason=f"{kind}:"
                      f"{fields.get('kind', fields.get('phase', '?'))}")

    def events(self, job_id: Optional[str] = None) -> List[dict]:
        with self._lock:
            if job_id is None:
                return list(self._global)
            return list(self._rings.get(job_id, ()))

    def set_trace(self, job_id: str, trace_id: Optional[str]) -> None:
        """Associate a propagated trace id with ``job_id`` (ISSUE 18)
        so that job's dumps can name the fleet request the ring
        belonged to (``trace_report --last-errors`` prints it)."""
        if not trace_id:
            return
        with self._lock:
            self._traces[job_id] = str(trace_id)

    def forget(self, job_id: str) -> None:
        with self._lock:
            self._rings.pop(job_id, None)
            self._traces.pop(job_id, None)

    def jobs(self) -> List[str]:
        with self._lock:
            return list(self._rings)

    # -- dumping -------------------------------------------------------
    def dump(self, job_id: Optional[str] = None,
             reason: str = "") -> Optional[dict]:
        """Emit one ``flight_dump`` record for the named ring (global
        when None) through the active tracer — or one compact stderr
        line when untraced. Returns the record (None when the ring is
        empty: nothing happened, nothing to dump)."""
        evs = self.events(job_id)
        if not evs:
            return None
        rec = {"job": job_id or GLOBAL_RING, "reason": reason,
               "n_events": len(evs), "events": evs}
        with self._lock:
            trace = self._traces.get(job_id) if job_id else None
            self.dumps += 1
        if trace:
            rec["trace"] = trace
        from sheep_tpu import obs

        tr = obs.get_tracer()
        if tr is not None:
            try:
                tr.emit("flight_dump", **rec)
            except Exception:
                pass  # forensics must never become the failure
        else:
            tail = ", ".join(e["ev"] for e in evs[-8:])
            print(f"sheep flight-recorder [{rec['job']}] {reason}: "
                  f"last {len(evs)} events: {tail}",
                  file=sys.stderr)
        return rec

    def dump_all(self, reason: str = "shutdown") -> int:
        """Dump the global ring plus every retained job ring (the
        daemon-shutdown sweep); returns how many dumps were emitted."""
        n = 0
        for jid in [None] + self.jobs():
            if self.dump(jid, reason=reason) is not None:
                n += 1
        return n
